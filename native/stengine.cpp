// stengine: the native steady-state link engine for host-tier peers.
//
// Round-3 measurement: the Python peer engine costs ~3 ms of interpreter
// work per wire message, capping small-table throughput at ~300 messages/s
// (~8.8 k frames/s at 4 Ki via 30-frame bursts) where the reference's bare
// C loop does 78 k frames/s (BASELINE.md; reference src/sharedtensor.c:
// 133-189 has no per-frame interpreter cost at all). This engine moves the
// whole steady-state cycle — scale/quantize (error feedback), wire encode,
// send, receive, decode, flood apply, ACK bookkeeping — into C, calling the
// same stcodec.c loops the numpy tier uses — bit-identical GIVEN the same
// scales; burst frames b >= 1 derive their scales from partials fused into
// the previous quantize pass (stc_quantize_ef_partials), whose summation
// order can differ from a standalone rescan by ~1 ulp, within the tier
// tolerance every scale consumer already accepts (scales ride the wire,
// receivers never recompute them) — and the sttransport.cpp queues
// directly. Python keeps only what is control-plane:
// join/SYNC handshakes, membership events, checkpoint, metrics.
//
// Semantics are a 1:1 port of the Python tier (comm/peer.py send/recv loops
// + core.SharedTensor), including:
//  - per-link residual error feedback with an unacked-message ledger;
//    rollback on link death restores undelivered frames bit-for-bit
//    (core.SharedTensor._unapply);
//  - cumulative per-message ACKs, counted even for undecodable DATA/BURST
//    (the sender's ledger pops per message — see comm/peer.py);
//  - split-horizon flood: an incoming frame applies to the replica and to
//    every OTHER link's residual (reference src/sharedtensor.c:124-127);
//  - BURST framing for small tables, DATA for large, non-finite scales
//    zeroed at the trust boundary, +/-3e38 saturation everywhere.
//
// Latency: the receiver BLOCKS on the transport's data-arrival condvar
// (st_node_wait_data) and the sender on an engine condvar poked by add(),
// attach and incoming floods — no polling floors (the Python tier's 2 ms
// recv sleep / 50 ms drain poll don't exist here).
//
// Locking mirrors the Python tier: ONE mutex over (values, residuals,
// ledgers); codec loops run under it; socket I/O outside it.

#include <unistd.h>

#include <cmath>

#include "st_annotations.h"  // clang -Wthread-safety vocabulary (no-op on gcc)
#include "st_cv.h"           // system-clock condvar deadlines (TSan arm)
#include <cstdint>
#include <cstdlib>
#include <cstring>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <set>
#include <string>
#include <thread>
#include <utility>
#include <vector>

// ---- imported C APIs (same-directory .so's, linked with $ORIGIN rpath) ---

extern "C" {
// stcodec.c
void stc_quantize(const float*, float*, const int64_t*, const int64_t*,
                  const int64_t*, int64_t, const float*, uint32_t*);
void stc_quantize_ef_partials(const float*, float*, const int64_t*,
                              const int64_t*, const int64_t*, int64_t,
                              const float*, uint32_t*, double*, double*,
                              double*);
void stc_scale_partials(const float*, const int64_t*, const int64_t*, int64_t,
                        double*, double*, double*);
void stc_accumulate_delta(float*, const int64_t*, const int64_t*,
                          const int64_t*, int64_t, const float*,
                          const uint32_t*);
void stc_add_to(float*, const float*, const float*, int64_t);
void stc_apply_frame(const float*, float*, const int64_t*, const int64_t*,
                     const int64_t*, int64_t, const float*, const uint32_t*);
void stc_accumulate_update_to(float*, const float*, const float*,
                              const int64_t*, const int64_t*, const int64_t*,
                              int64_t);
void stc_accumulate_update_to_partials(float*, const float*, const float*,
                                       const int64_t*, const int64_t*,
                                       const int64_t*, int64_t, double*,
                                       double*, double*);
void stc_apply_frames(const float*, float*, const int64_t*, const int64_t*,
                      const int64_t*, int64_t, int64_t, int32_t, const float*,
                      const uint32_t*, double*, double*, double*);
// r11 cascade quantize (K halving frames in one pass) + sign2 (2-bit)
// kernels — see stcodec.c's r11 section for semantics and layout.
void stc_quantize_ef_cascade(const float*, float*, const int64_t*,
                             const int64_t*, const int64_t*, int64_t, int32_t,
                             const float*, uint32_t*, int64_t, double*,
                             double*, double*);
void stc_quantize2_ef_cascade(const float*, float*, const int64_t*,
                              const int64_t*, const int64_t*, int64_t,
                              int32_t, const float*, uint32_t*, int64_t,
                              int64_t, double*, double*, double*);
void stc_apply_frames2(const float*, float*, const int64_t*, const int64_t*,
                       const int64_t*, int64_t, int64_t, int32_t,
                       const float*, const uint32_t*, double*, double*,
                       double*);
// r14 wire-layout fused applies: read scales/words straight from the
// (4-aligned, v3-framed) wire body — no repack copy.
void stc_apply_frames_wire(const float*, float*, const int64_t*,
                           const int64_t*, const int64_t*, int64_t, int64_t,
                           int32_t, const uint8_t*, int64_t, double*,
                           double*, double*);
void stc_apply_frames2_wire(const float*, float*, const int64_t*,
                            const int64_t*, const int64_t*, int64_t, int64_t,
                            int32_t, const uint8_t*, int64_t, double*,
                            double*, double*);
void stc_apply_frame2(const float*, float*, const int64_t*, const int64_t*,
                      const int64_t*, int64_t, int64_t, const float*,
                      const uint32_t*);
// sttransport.cpp
int32_t st_node_send(void*, int32_t, const uint8_t*, int32_t, double);
// zero-copy enqueue: the transport borrows the payload (no copy) and calls
// release(ctx) exactly once after the socket write / at teardown; on a
// non-1 return it took no ownership (see sttransport.cpp st_node_send_zc)
int32_t st_node_send_zc(void*, int32_t, const uint8_t*, int32_t, double,
                        void (*)(void*), void*);
int32_t st_node_recv(void*, int32_t, uint8_t*, int32_t, double);
// r14 zero-copy receive: the transport LOANS the popped rx buffer (valid
// until the next recv_zc/recv_done on the same link) instead of copying
// it out — one full-message copy gone from the receive hot path, on every
// lane (TCP, striped, shm).
int32_t st_node_recv_zc(void*, int32_t, const uint8_t**, double);
void st_node_recv_done(void*, int32_t);
// r17 shard plane: ownership-transfer receive (the transport half of the
// zero-copy verbatim relay) + sendq headroom probe (the _queue_room
// discipline). See sttransport.cpp for semantics.
int32_t st_node_recv_take(void*, int32_t, const uint8_t**, void**);
void st_node_take_free(void*, int32_t, void*);
int32_t st_node_sendq_room(void*, int32_t);
int32_t st_node_drop_link(void*, int32_t);
uint64_t st_node_data_seq(void*);
uint64_t st_node_wait_data(void*, uint64_t, double);
// Fault-injection crash point (ST_FAULT_CRASH="point:N"; ONE parse/countdown
// for the whole .so, defined in sttransport.cpp — see its docstring). The
// engine's protocol points: "mid-burst" (frames quantized + ledgered,
// message NOT yet on the wire) and "between-apply-and-ack" (mass applied +
// flooded, ACK not yet sent — the two-generals at-least-once window).
// comm/faults.py documents the schedule format and renders FaultConfig
// into it (to_env).
void st_fault_crash_point(const char*);
// r08 obs event ring (defined once in sttransport.cpp; codes are ABI —
// obs/events.py CODE_NAMES is the authoritative mirror). Engine-side
// events: retransmit(10), black-hole teardown(11), quarantine(12),
// send-window stall(13, edge-triggered), dedup/gap discard(14), seal(15),
// trace-hop apply(30, r09 — emitted per accepted traced data message with
// (origin << 8 | hop) packed into the record's extra word).
void st_obs_emit(uint32_t node_id, uint32_t code, int32_t link, uint64_t arg);
void st_obs_emit2(uint32_t node_id, uint32_t code, int32_t link, uint64_t arg,
                  uint32_t extra);
uint64_t st_obs_now_ns();
int32_t st_obs_is_enabled();
uint32_t st_node_obs_id(void*);
}

namespace {

// wire message kinds (comm/wire.py)
constexpr uint8_t kData = 0;
constexpr uint8_t kAck = 6;
constexpr uint8_t kBurst = 7;
// r10 serving tier: FRESH = parent's drained-residual freshness mark for a
// subscriber link ([kind][u64 monotonic ns]); RDATA = one frame sliced to
// the subscribed word range ([kind][u32 seq][u32 word_lo][u32 word_cnt]
// [trace?][scales L*4][words word_cnt*4]). Both are emitted by this
// sender for subscriber-mode links only; neither is ever received here
// (subscribers run the Python serve tier).
constexpr uint8_t kFresh = 10;
constexpr uint8_t kRData = 11;

constexpr float kSat = 3.0e38f;

// Go-back-N send window / per-round retransmission prefix (comm/peer.py
// SEND_WINDOW / RETX_PREFIX — same bounds, same rationale: cap a stalled
// link's retained ledger memory, and re-send only the head that can
// actually restore in-order progress at the receiver).
constexpr size_t kSendWindow = 32;
constexpr size_t kRetxPrefix = 4;
// Frames per message on SUBSCRIBER links (r10), capping the writer-tier
// burst: a serving link trades batch efficiency for pipeline LATENCY —
// its staleness floor is (transport queue depth) x (per-message apply
// time at the python-tier subscriber), so 255-frame multi-MB bursts put
// the floor at seconds while 32 keeps it near the read bound. Writers'
// writer links keep the full burst (peer.py SEND_WINDOW rationale).
constexpr int kSubBurstCap = 32;

// scale policies (config.ScalePolicy)
enum Policy { kPow2Rms = 0, kRms = 1, kAbsMean = 2 };

// ---- tx slot ring (r07 zero-copy data plane) ------------------------------
//
// A TxSlot is one preallocated wire-message buffer shared by every stage
// that used to copy: the codec threads QUANTIZE DIRECTLY into it (scales +
// sign words land at their final wire offsets), the go-back-N ledger entry
// IS the slot (retransmission is trivially byte-identical — the bytes are
// never re-encoded), and the transport sends it zero-copy (st_node_send_zc
// + writev: length prefix and slot body gather in one syscall). The old
// path built msg vectors, encoded them into a payload vector, and
// st_node_send copied that again — three full-message copies plus a fresh
// multi-MB allocation per message, all gone.
//
// Layout: buf[8..] is the frame body (frame f's scales at f*per, words at
// f*per + 4L — per = 4L + 4W is a multiple of 4, so with the body
// 8-aligned every codec pointer the kernels receive is properly aligned;
// UBSan-clean). The wire header is packed immediately BEFORE the body:
// BURST [kind][u32 seq][u8 k] at offset 2, DATA [kind][u32 seq] at offset
// 3, so wire_off + header + body are contiguous without moving the body.
//
// Lifecycle is a refcount: the ledger holds one reference from encode
// until ACK/rollback; each in-flight transport enqueue (first send AND
// every retransmit) holds another, dropped by the transport's release
// callback after the socket write. SEND_WINDOW times out-of-order ACKs
// bound the live slots per link; the free list keeps a few buffers warm
// and frees the rest, so a burst's high-water mark doesn't pin memory.
struct TxPool;

struct TxSlot {
  std::vector<uint8_t> buf;
  uint32_t wire_off = 0, wire_len = 0;
  std::atomic<int32_t> refs{0};
  TxPool* pool = nullptr;
};

struct TxPool {
  StMutex mu;
  std::vector<TxSlot*> free_ ST_GUARDED_BY(mu);
  std::vector<std::unique_ptr<TxSlot>> all_ ST_GUARDED_BY(mu);
  // written between create and start only (st_engine_set_codec); the
  // sender thread reads it unlocked after the start fence
  size_t slot_bytes = 0;   // 8 + burst * frame_bytes
  size_t keep_warm = 4;    // free slots retained with their buffer intact
  size_t warm_ ST_GUARDED_BY(mu) = 0;  // free_ entries with buf intact
                                       // (all at the back)
  std::atomic<uint64_t> acquires{0}, alloc_events{0};

  TxSlot* acquire() {
    acquires++;
    TxSlot* s;
    {
      StLockGuard lk(mu);
      if (!free_.empty()) {
        s = free_.back();
        free_.pop_back();
        if (warm_ > 0 && !s->buf.empty()) warm_--;
      } else {
        all_.emplace_back(new TxSlot());
        s = all_.back().get();
        s->pool = this;
      }
    }
    if (s->buf.size() != slot_bytes) {
      alloc_events++;  // fresh slot, or re-grow after an idle shrink
      s->buf.resize(slot_bytes);
    }
    s->refs.store(1, std::memory_order_relaxed);  // the caller's reference
    return s;
  }

  void unref(TxSlot* s) {
    // the decrement happens UNDER the pool mutex: st_engine_destroy's
    // drain loop checks all refs under the same mutex, so it can never
    // observe "all drained" while a releaser sits between its decrement
    // and the free-list push (it would then free the pool under us)
    StLockGuard lk(mu);
    if (s->refs.fetch_sub(1, std::memory_order_acq_rel) == 1) {
      if (warm_ >= keep_warm) {
        // bound idle memory: keep the slot object, drop its buffer — and
        // park it at the COLD end of the list so acquire() (which pops
        // the back) keeps hitting the warm buffers. The bound counts
        // WARM free slots (warm_), not the free list's length: once a
        // window stall grew the pool, the list stays longer than
        // keep_warm forever even though most entries are cold, and a
        // length-based check then shrank every returning slot — each
        // steady-state message paid a multi-MB value-initializing
        // resize + page faults under the data-plane mutex (measured
        // ~1.7 ms of the 1 Mi sender's 3.3 ms pass wall).
        s->buf.clear();
        s->buf.shrink_to_fit();
        free_.insert(free_.begin(), s);
      } else {
        free_.push_back(s);
        warm_++;
      }
    }
  }
};

// transport release callback: one in-flight reference returned
void tx_slot_release(void* ctx) {
  auto* s = (TxSlot*)ctx;
  s->pool->unref(s);
}

// obs event codes the engine emits (mirror of sttransport.cpp stobs::kEv*)
constexpr uint32_t kEvRetransmit = 10;
constexpr uint32_t kEvBlackhole = 11;
constexpr uint32_t kEvQuarantine = 12;
constexpr uint32_t kEvWindowStall = 13;
constexpr uint32_t kEvDedupDiscard = 14;
constexpr uint32_t kEvSeal = 15;
constexpr uint32_t kEvTraceApply = 30;  // r09 cross-hop trace propagation
constexpr uint32_t kEvSubAttach = 31;   // r10 subscriber link attached
constexpr uint32_t kEvPrecShift = 32;   // r11 governor flipped link precision

// r11 adaptive precision: the kind byte's top bit marks a sign2 (2-bit)
// DATA/BURST message — body per frame is [scales L*4][sign W*4][mag W*4]
// instead of [scales][sign]. Receivers here tolerant-decode BOTH widths
// unconditionally (precision bit selects the frame size; message length
// still disambiguates the r09 v1/v2 trace framing within each width), and
// EMISSION is gated per link on the peer's advertised capability
// (compat.SYNC_FLAG_SIGN2 / WELCOME flags -> st_engine_link_allow_sign2),
// so mixed trees interop: a pre-r11 peer never advertises and never
// receives a 2-bit frame.
constexpr uint8_t kPrecBit = 0x80;

// ---- r09 trace context (comm/wire.py v2 framing) --------------------------
//
// DATA v2: [kind u8][seq u32][origin u32][origin_ns u64][hops u8][body]
// BURST v2: [kind u8][seq u32][k u8][origin u32][origin_ns u64][hops u8][body]
// The 13-byte trace context stamps each outgoing message with the causal
// provenance of the LATEST update folded into this node's residuals: a
// local add() re-seeds it (origin = this node, hops = 0); applying a
// traced foreign message advances it (origin/gen preserved, hops + 1).
// Receivers accept BOTH v1 (r08, 5/6-byte headers) and v2 sizes — per is a
// multiple of 4 and the trace adds 13, so message length disambiguates the
// version unambiguously and mixed-version trees interop (the version gate
// lives in compat.py / ObsConfig.trace_wire; SYNC advertises it).
constexpr size_t kTraceBytes = 13;
constexpr size_t kDataHdrV1 = 5, kBurstHdrV1 = 6;
constexpr size_t kDataHdrV2 = kDataHdrV1 + kTraceBytes;   // 18
constexpr size_t kBurstHdrV2 = kBurstHdrV1 + kTraceBytes;  // 19
// r14 "aligned" v3 framing — ONE 24-byte header for DATA and BURST:
// [kind u8][k u8][pad u16][seq u32][origin u32][gen u64][hops u8][pad*3].
// Sized so the frame body lands 8-ALIGNED in the receiver's buffer, which
// lets the fused apply read scales/words straight from the wire body
// (stc_apply_frames_wire) — the receive path's full-message repack (one
// read + one write of every wire byte) disappears. Emission is gated per
// link on the peer's advertised r14 capability (the SYNC/WELCOME shm
// flag doubles as the r14 marker — compat.py) AND on trace_wire (the
// trace context is a fixed field here); decode is unconditional and
// length-disambiguated from v1/v2 exactly like r09's bump: per is a
// multiple of 4, and 24 ≡ 0 (mod 4) collides with neither 5/18 (kData)
// nor 6/19 (kBurst). The trace context occupies bytes 8..20, the same
// contiguous [origin u32][gen u64][hops u8] order v2 carries.
constexpr size_t kHdrV3 = 24;
// Header room reserved before a tx slot's 8-aligned frame body (was 8 in
// r07; v2's largest header is 19 bytes, so the room grows to the next
// multiple of 8 — the body stays aligned for the codec kernels).
constexpr size_t kBodyOff = 24;

struct SentMsg {
  // one wire message = 1..k frames; rolls back / acks whole
  int32_t nframes;
  // frame precision (r11): 1 = sign-bit frames, 2 = sign2 (2-bit) frames —
  // rollback must re-apply each ledgered frame with the matching kernel
  uint8_t prec = 1;
  uint64_t seq = 0;      // per-link wire seq (comm/wire.py tx_seq)
  // ledger-append time: ACK-pop minus this is the delivery round trip the
  // r08 RTT counters aggregate (st_engine_counters[10..11]); includes any
  // retransmission rounds, which is what an operator debugging a slow link
  // wants the number to include
  std::chrono::steady_clock::time_point sent_at{};
  TxSlot* slot = nullptr;  // native framing: the encoded wire bytes
                           // (this ledger entry owns one pool reference)
  std::vector<float> scales;    // compat path only: nframes * L
  std::vector<uint32_t> words;  // compat path only: nframes * W
};

using EClock = std::chrono::steady_clock;

struct ELink {
  std::vector<float> resid;
  std::deque<SentMsg> unacked;
  uint64_t acked_cum = 0;  // cumulative ACK count received from the peer
  uint64_t tx_seq = 0;     // wire seq of the last DATA/BURST sent
  // last IN-ORDER wire seq accepted from the peer (== cumulative accepted
  // messages; comm/wire.py tx_seq discipline). Doubles as the ACK value.
  uint64_t rx_count = 0;
  uint64_t ack_sent = 0;   // highest ACK value actually delivered
  // go-back-N delivery timer (Engine::ack_timeout): time of the link's
  // last delivery progress, and fruitless retransmission rounds since
  EClock::time_point ack_progress{};
  int32_t retx_rounds = 0;
  // edge detector for the send-window stall event (kEvWindowStall): emit
  // once per blocked episode, not once per sender pass (a stalled link
  // would otherwise spam the ring at wake frequency)
  bool window_blocked = false;
  bool dirty = true;       // residual may quantize to something nonzero
  bool dead = false;       // transport reported death; stop touching
  // Scale-partials cache for this residual: every pass that already walks
  // the residual (quantize, flood apply, add) refreshes it fused, so the
  // sender's standalone stc_scale_partials scan — a full-table read per
  // message, 1/3 of sender traffic at 16 Mi — only runs after the rare
  // writes that bypass the fused kernels (rollback, restore). pvalid
  // guards staleness; all access under Engine::mu.
  std::vector<double> pamax, pss, psabs;
  bool pvalid = false;
  // r09 convergence telemetry (st_engine_link_obs): origin-stamp age of the
  // latest traced message applied FROM this link, and its hop distance.
  // Updated at flush under Engine::mu.
  uint64_t stale_ns = 0;
  uint32_t last_hops = 0;
  // r10 subscriber link mode (st_engine_attach_sub): read-only leaf on the
  // other end — UNLEDGERED (no unacked entries, no ACKs expected, no
  // go-back-N; loss shows up as a seq gap the subscriber repairs with a
  // resync handshake), optionally RANGE-FILTERED (only words
  // [wlo, wlo+wcnt) of each frame ship, as kRData messages — the
  // paged-subscription discipline), with periodic kFresh drain marks so an
  // idle subscriber can still verify its staleness bound.
  bool subscriber = false;
  bool ranged = false;
  int64_t wlo = 0, wcnt = 0;  // subscribed word range
  uint64_t fresh_interval_ns = 0;
  uint64_t last_fresh_ns = 0;
  // r11 adaptive precision. peer_sign2: the OTHER end advertised sign2
  // decode capability (SYNC/WELCOME flags; emission is gated on it — see
  // kPrecBit). prec: the governor's current choice for this link (1 or 2).
  // gov_*: the telemetry loop's state — previous residual RMS sample and
  // consecutive stall/quiet votes (2 votes with hysteresis, so one noisy
  // interval can't flap the link).
  //
  // Byte-bound gating (the loop's stability half): sign2 buys more
  // residual mass PER BYTE (the lab measurement this PR promotes) at 2x
  // the bytes per frame — so the upshift only pays when BYTES are the
  // link's scarce resource. A loopback/compute-bound link at its
  // equilibrium is frame-bound, not byte-bound: upshifting it just
  // halves the frame rate, and the rms there is a flat sawtooth whose
  // discrete jitter (integer multiples of one add's norm) defeats every
  // trend-based verdict — both a one-shot probation (a mark captured
  // during the join transient "passes" forever: bimodal 26-vs-44 GB/s
  // bench runs) and a continuous-progress rule (sawtooth dips read as
  // progress: flapping). The honest discriminator is direct byte
  // BACKPRESSURE, which the send path already observes: a send attempt
  // that sat out its full timeout on a full sendq (gov_bp, counted per
  // beat) or a go-back-N window that closed (window_blocked — the peer
  // acks slower than we produce). Healthy loopback shows NEITHER
  // (measured: zero events over 8 s saturated), a capped or
  // NIC-saturated or chaos-storm link shows them continuously. Growth
  // votes therefore only count while byte-bound, and sign2 holds
  // exactly as long as the byte-bound condition does (kGovStall quiet
  // beats to lift, so a bursty storm doesn't flap the link) or the
  // residual quiesces.
  bool peer_sign2 = false;
  // r14: the peer decodes the aligned v3 framing (advertised via the
  // SYNC/WELCOME r14 capability flag; st_engine_link_wire_v3)
  bool wire_v3 = false;
  int prec = 1;
  double gov_prev = -1.0;
  uint64_t gov_last_ns = 0;
  int gov_up = 0, gov_down = 0;
  uint32_t gov_bp = 0;   // byte-backpressure events since the last beat
  int gov_quiet = 0;     // consecutive beats without byte pressure
};

constexpr int kGovStall = 8;  // quiet beats before sign2 stands down
                              // (~0.8 s at the default beat)

struct Engine {
  void* node = nullptr;
  int64_t L = 0, total = 0, total_n = 0, W = 0;
  std::vector<int64_t> off, ns, padded;
  int policy = kPow2Rms;
  bool per_leaf = true;
  int burst = 1;         // frames per BURST message (1 => DATA framing)
  int32_t recv_cap = 0;  // recv buffer size (max wire message)
  // Per-link send quarantine (TransportConfig.quarantine_send_failures):
  // after this many CONSECUTIVE backpressure failures (~0.1 s each) the
  // link is torn down via st_node_drop_link and re-grafted instead of
  // retried hot — a peer that stopped draining but kept its socket open
  // would otherwise pin this sender until the liveness timeout.
  // 0 = disabled (retry until the liveness timeout kills the link).
  int32_t quarantine = 0;
  // Go-back-N delivery timer (TransportConfig.ack_timeout_sec): when a
  // link's oldest unacked message has waited this long, the sender
  // retransmits the whole unacked tail byte-identical (same wire seqs —
  // the receiver dedups, so a spurious retransmit is harmless). After
  // ack_retry_limit fruitless rounds the link is a black hole and is torn
  // down for re-graft. 0 = disabled. Native framing only (compat has no
  // ACKs at all).
  double ack_timeout = 0.0;
  // Retransmission rounds with zero ACK progress before a link is declared
  // a black hole and torn down for re-graft
  // (TransportConfig.ack_retry_limit; same knob as the Python tier).
  int32_t ack_retry_limit = 8;
  // Wire-compat mode (reference raw protocol, comm/wire.py
  // encode_compat_frame): every wire message is exactly compat_bytes =
  // [f32 scale LE][ceil(n/8) bitmask bytes] — no kind byte, no bursts, no
  // ACKs (so no ledger: the reference protocol cannot acknowledge).
  // 0 = native framing.
  int32_t compat_bytes = 0;

  TxPool txpool;  // native-framing tx slot ring (see TxSlot)

  // Data-plane mutex (mirrors the Python tier: ONE lock over values,
  // residuals, ledgers; codec loops run under it, socket I/O outside it —
  // except flush_acks/FRESH beats, which send with a ZERO timeout from
  // under it by design). Declared before the fields it guards so the
  // ST_GUARDED_BY references resolve.
  StMutex mu;
  std::vector<float> values ST_GUARDED_BY(mu);
  // The whole ELink record — residual, ledger, governor state — is guarded
  // by mu as a unit: the analysis checks every access to the map itself,
  // and no code path retains an ELink reference across an unlock.
  std::map<int32_t, ELink> links ST_GUARDED_BY(mu);
  // The re-graft carry as a LIVE slot (the reference's unconnected-slot
  // mechanism, src/sharedtensor.c:124-126/:338-342): a dead uplink's
  // rolled-back residual parks here and KEEPS accumulating add()/flood
  // mass while the node is orphaned — an add made with no links must ride
  // the re-graft, or the join snapshot presents it as tree-known state and
  // the parent's diff seed erases it everywhere (measured as tree-wide
  // loss in the churn soak before this existed).
  std::vector<float> carry ST_GUARDED_BY(mu);
  bool has_carry ST_GUARDED_BY(mu) = false;

  // r11 staged adds: st_engine_add used to take the data-plane mutex for
  // its two full-table passes, serializing every trainer add behind
  // whatever multi-pass message quantize held it (measured: 2.9 ms per
  // add at 1 Mi under load, the saturated pipeline's limiter). Adds now
  // accumulate into `upend` under add_mu ONLY — sanitize+clip fused, the
  // same kernel — and every data-plane path that reads values/residuals
  // folds the pending sum in first (fold_pending: the old add body, run
  // under e->mu at the next safe point). Lock order: e->mu -> add_mu,
  // never the reverse; add() takes only add_mu. The pending trace
  // re-seed stages through pend_gen the same way.
  StMutex add_mu ST_ACQUIRED_AFTER(mu);
  // upend: the trainers' staged accumulation (add_mu alone). ufold: the
  // fold scratch — swapped in under BOTH locks (fold_pending), then read
  // and re-zeroed under mu alone, so mu is its guard.
  std::vector<float> upend ST_GUARDED_BY(add_mu);
  std::vector<float> ufold ST_GUARDED_BY(mu);
  std::atomic<bool> has_pending{false};
  std::atomic<uint64_t> pend_gen{0};

  // sender wake (missed-wakeup-safe sequence counter)
  StMutex wmu;
  std::condition_variable wcv;
  uint64_t wseq ST_GUARDED_BY(wmu) = 0;

  // control messages (non DATA/BURST/ACK) surfaced to Python
  StMutex cmu;
  std::deque<std::pair<int32_t, std::vector<uint8_t>>> ctrl
      ST_GUARDED_BY(cmu);

  std::atomic<bool> stop{false};
  // Sender pass counter (r12): incremented at the top of every sender-loop
  // iteration. st_engine_pause's synchronous wait uses it to bound the one
  // in-flight pass that may still enqueue data produced from pre-pause
  // state — the barrier's SNAP marker must follow the sender's LAST data
  // message on every link, and a marker enqueued while a pass is mid-
  // flight would otherwise be overtaken (consistent-cut ordering).
  std::atomic<uint64_t> sender_pass{0};
  // r12 lifecycle quiesce (st_engine_pause): the sender produces NO new
  // data frames while paused — quantize/encode/send of fresh residual mass
  // stops, so the cluster-wide consistent cut can drain every in-flight
  // ledger to empty. Everything else keeps running: ACK processing,
  // go-back-N retransmission (in-flight delivery must COMPLETE for the
  // barrier to quiesce), control traffic, and FRESH beats on already-
  // drained subscriber links (they only fire when the residual is empty,
  // so a paused-but-current subscriber keeps verifying its bound instead
  // of going stale — and a paused-with-mass one gets no mark, so a read
  // across the cut can never falsely verify).
  std::atomic<bool> paused{false};
  // Sealed ingress (graceful-leave step 1): DATA/BURST messages are popped
  // and DISCARDED — not applied, not counted, not ACKed — so their senders'
  // ledgers keep them and re-deliver after our departure's re-graft. This
  // closes the leave-time loss window: without it, a frame applied+ACKed
  // in the instant between drain()'s last check and close() puts mass into
  // residuals that die with us, and its sender (holding our ACK) never
  // re-sends. ACK and control handling continue (our own drain needs them).
  std::atomic<bool> sealed{false};
  std::atomic<uint64_t> frames_out{0}, frames_in{0}, updates{0};
  std::atomic<uint64_t> msgs_out{0}, msgs_in{0};
  // r08 obs counters (st_engine_counters[8..11]): go-back-N retransmitted
  // messages, dup/gap discards at the receive acceptance check, and the
  // ACK round-trip aggregate (sum of ns + sample count — the C hot path
  // keeps no buckets; Python renders mean / exports sum+count).
  std::atomic<uint64_t> retx_msgs{0}, dedup_discards{0};
  std::atomic<uint64_t> rtt_ns_total{0}, rtt_msgs{0};
  // r09 trace aggregates (st_engine_counters[12..15]): hop-count sum +
  // sample count over applied traced messages (st_update_hops on the
  // Python tier keeps buckets; the C hot path exports sum/count like the
  // RTT pair), the most recent apply-time staleness, and how many applied
  // data messages carried a v2 trace stamp at all.
  std::atomic<uint64_t> hops_sum{0}, hops_msgs{0};
  std::atomic<uint64_t> staleness_ns_last{0};
  std::atomic<uint64_t> traced_msgs_in{0};
  // r10 serving tier (st_engine_counters[16..17]): unledgered data
  // messages sent to subscriber links (OUTSIDE the msgs_out taxonomy —
  // that one stays "ACK-ledgered wire messages" on both tiers) and kFresh
  // drain marks delivered.
  std::atomic<uint64_t> sub_msgs_out{0}, sub_fresh_out{0};
  // r11 adaptive precision (st_engine_counters[18..21]): governor
  // upshifts/downshifts, and sign2 frames sent/applied (subsets of
  // frames_out/frames_in — the taxonomy equalities are precision-blind).
  std::atomic<uint64_t> prec_upshifts{0}, prec_downshifts{0};
  std::atomic<uint64_t> frames2_out{0}, frames2_in{0};
  // r11 codec config (st_engine_set_codec; called between create and
  // start). prec_mode: 0 = fixed 1-bit, 1 = telemetry-adaptive (the
  // governor may upshift capable links to sign2), 2 = fixed sign2 on
  // capable links (A/B arms). gov_up_ratio: upshift when the residual RMS
  // fails to decay below ratio*previous for 2 consecutive beats (the
  // 1-bit codec is not keeping up); gov_down_ratio: downshift when it
  // decays below this ratio (or quiesces). cascade: frames quantized per
  // memory pass on the ledgered 1-bit/sign2 paths (1 = the r10 per-frame
  // re-measured schedule).
  int prec_mode = 0;
  double gov_up_ratio = 1.05, gov_down_ratio = 0.5;
  double gov_interval = 0.1;
  int cascade = 1;
  // r09 wire format: stamp outgoing DATA/BURST with the v2 trace context
  // (0 = v1 framing, byte-identical to r08 — the receive side accepts
  // both regardless, so mixed trees interop; ObsConfig.trace_wire).
  int32_t trace_wire = 0;
  // Pending trace stamp (under mu): provenance of the latest update folded
  // into the residuals — re-seeded by add() (this node, now, 0 hops),
  // advanced by every traced apply (origin kept, hops + 1). Approximate by
  // design: residual coalescing means one outgoing message can carry many
  // generations' mass; it is stamped with the newest (README "Cluster
  // observability" documents the semantics).
  uint32_t t_origin ST_GUARDED_BY(mu) = 0;
  uint64_t t_gen ST_GUARDED_BY(mu) = 0;
  uint32_t t_hops ST_GUARDED_BY(mu) = 0;
  bool t_has ST_GUARDED_BY(mu) = false;
  uint32_t obs_id = 0;  // the node's process-unique obs id (event tag)
  std::thread send_thread, recv_thread;

  void wake() ST_EXCLUDES(wmu) {
    {
      StLockGuard lk(wmu);
      wseq++;
    }
    wcv.notify_all();
  }
};

// Fold the staged pending add (st_engine_add) into values + every
// residual + the carry — the pre-r11 add body, run at the next safe
// point by whoever holds e->mu. One atomic-bool check when idle.
void fold_pending(Engine* e) ST_REQUIRES(e->mu) {
  if (!e->has_pending.load(std::memory_order_acquire)) return;
  {
    StLockGuard alk(e->add_mu);
    if (!e->has_pending.load(std::memory_order_relaxed)) return;
    // fold scratch sized lazily HERE (under both locks — ufold is
    // mu-guarded, and st_engine_add holds only add_mu)
    if (e->ufold.size() != e->upend.size())
      e->ufold.assign(e->upend.size(), 0.0f);
    // swap the accumulation buffer out (ufold is pre-zeroed — see the
    // fill below) so concurrent adds keep landing while we fold
    std::swap(e->upend, e->ufold);
    e->has_pending.store(false, std::memory_order_release);
  }
  const float* u = e->ufold.data();
  stc_accumulate_update_to(e->values.data(), e->values.data(), u,
                           e->off.data(), e->ns.data(), e->padded.data(),
                           e->L);
  for (auto& kv : e->links) {
    ELink& lk2 = kv.second;
    if ((int64_t)lk2.pamax.size() != e->L) {
      lk2.pamax.resize((size_t)e->L);
      lk2.pss.resize((size_t)e->L);
      lk2.psabs.resize((size_t)e->L);
    }
    stc_accumulate_update_to_partials(
        lk2.resid.data(), lk2.resid.data(), u, e->off.data(), e->ns.data(),
        e->padded.data(), e->L, lk2.pamax.data(), lk2.pss.data(),
        lk2.psabs.data());
    lk2.pvalid = true;
    lk2.dirty = true;
  }
  if (e->has_carry)
    stc_accumulate_update_to(e->carry.data(), e->carry.data(), u,
                             e->off.data(), e->ns.data(), e->padded.data(),
                             e->L);
  std::fill(e->ufold.begin(), e->ufold.end(), 0.0f);  // ready for re-swap
  uint64_t g = e->pend_gen.exchange(0, std::memory_order_acq_rel);
  if (e->trace_wire && g) {
    // a local update is a fresh generation: re-seed the pending stamp
    // (origin = this node, generation = the add's clock reading, 0 hops)
    e->t_origin = e->obs_id;
    e->t_gen = g;
    e->t_hops = 0;
    e->t_has = true;
  }
}

// scale = policy(partials); zero when the leaf is all-zero or the result is
// non-finite. Same math as ops/codec_np.compute_scales_np's native branch:
// double math, cast to f32, pow2-floor by exponent mask.
void scales_from_partials(Engine* e, const std::vector<double>& amax,
                          const std::vector<double>& ss,
                          const std::vector<double>& sabs, float* out) {
  // NON-mutating (the inputs may be a link's partials cache): the
  // aggregate for per_leaf == false lives in locals.
  double g_am = 0, g_s2 = 0, g_sa = 0;
  if (!e->per_leaf) {
    for (int64_t i = 0; i < e->L; i++) {
      if (amax[i] > g_am) g_am = amax[i];
      g_s2 += ss[i];
      g_sa += sabs[i];
    }
  }
  for (int64_t i = 0; i < e->L; i++) {
    double n = e->per_leaf ? (double)e->ns[i] : (double)e->total_n;
    double am = e->per_leaf ? amax[i] : g_am;
    double s2 = e->per_leaf ? ss[i] : g_s2;
    double sa = e->per_leaf ? sabs[i] : g_sa;
    float s;
    if (e->policy == kAbsMean) {
      s = (float)(sa / n);
    } else {
      s = (float)std::sqrt(s2 / n);
      if (e->policy == kPow2Rms) {
        union {
          float f;
          uint32_t u;
        } b;
        b.f = s;
        b.u &= 0x7F800000u;  // 2^floor(log2 s); subnormals -> 0
        s = b.f;
      }
    }
    out[i] = (am > 0 && std::isfinite(s)) ? s : 0.0f;
  }
}

bool any_nonzero(const float* s, int64_t L) {
  for (int64_t i = 0; i < L; i++)
    if (s[i] != 0.0f) return true;
  return false;
}

// Roll every unacked message's error feedback back into the residual
// (core.SharedTensor._unapply: re-applying a frame to the residual restores
// the pre-quantize state bit-for-bit). Native-framing entries read their
// frames straight out of the ledgered tx slot (the slot body offsets are
// 4-aligned by construction — see TxSlot) and drop the ledger's pool
// reference. Caller holds e->mu.
void rollback_unacked(Engine* e, ELink& lk) ST_REQUIRES(e->mu) {
  size_t per = (size_t)e->L * 4 + (size_t)e->W * 4;
  for (auto& msg : lk.unacked) {
    // frame stride follows the ledgered message's precision (r11): a
    // sign2 frame carries a second (magnitude) word plane
    size_t fb = msg.prec == 2 ? per + (size_t)e->W * 4 : per;
    for (int32_t f = 0; f < msg.nframes; f++) {
      const float* fs;
      const uint32_t* fw;
      if (msg.slot) {
        const uint8_t* body = msg.slot->buf.data() + kBodyOff + (size_t)f * fb;
        fs = (const float*)body;
        fw = (const uint32_t*)(body + (size_t)e->L * 4);
      } else {
        fs = msg.scales.data() + (size_t)f * e->L;
        fw = msg.words.data() + (size_t)f * e->W;
      }
      if (msg.prec == 2)
        stc_apply_frame2(lk.resid.data(), lk.resid.data(), e->off.data(),
                         e->ns.data(), e->padded.data(), e->L, e->W, fs, fw);
      else
        stc_apply_frame(lk.resid.data(), lk.resid.data(), e->off.data(),
                        e->ns.data(), e->padded.data(), e->L, fs, fw);
    }
    if (msg.slot) e->txpool.unref(msg.slot);
  }
  lk.unacked.clear();
  lk.pvalid = false;  // rollback bypasses the fused-partials kernels
}

// Apply k decoded frames from `src_link` to the replica and every OTHER
// link's residual (split-horizon flood). Caller holds e->mu.
// prec (r11): 1 = sign-bit frames (words is k*W), 2 = sign2 frames (words
// is k*2W — per frame, sign plane then magnitude plane). A receive batch
// flushes on precision change, so one call is always homogeneous.
void apply_batch(Engine* e, int32_t src_link, int32_t k, const float* scales,
                 const uint32_t* words, int prec) ST_REQUIRES(e->mu) {
  // NOTE: dead links are NOT skipped here (only the I/O loops skip them):
  // a dead link's residual keeps accumulating until Python detaches it —
  // that residual IS the carry the re-graft owes, and mass applied in the
  // death-to-detach window would otherwise vanish from the carry AND be
  // claimed by the re-join snapshot, losing it tree-wide
  // (core.SharedTensor applies to all links until drop_link, same reason).
  // Corruption-zeroed (all-zero-scale) frames apply as no-ops and must
  // count NOWHERE: the metrics taxonomy promises a quiesced pair satisfies
  // sender.frames_out == receiver.frames_in (idle frames count on neither
  // side), and a sender never emits all-zero frames — counting a zeroed
  // frame here would read as a phantom discrepancy exactly when an
  // operator is debugging a corrupt link.
  uint64_t applied = 0;
  for (int32_t f = 0; f < k; f++)
    if (any_nonzero(scales + (size_t)f * e->L, e->L)) applied++;
  if (applied == 0) return;
  // k-frame fused apply (stc_apply_frames / its sign2 twin): ONE pass per
  // target regardless of k — no delta buffer (the old k>1 path paid k
  // read-modify-write passes over a total*4 delta before touching any
  // target; at 16 Mi that was k*128 MiB of traffic). Residual targets
  // refresh their scale-partials cache in the same pass (ELink::pvalid).
  auto apply = [&](const float* in, float* out, double* pa, double* ps,
                   double* pb) {
    if (prec == 2)
      stc_apply_frames2(in, out, e->off.data(), e->ns.data(),
                        e->padded.data(), e->L, e->W, k, scales, words, pa,
                        ps, pb);
    else
      stc_apply_frames(in, out, e->off.data(), e->ns.data(),
                       e->padded.data(), e->L, e->W, k, scales, words, pa,
                       ps, pb);
  };
  apply(e->values.data(), e->values.data(), nullptr, nullptr, nullptr);
  for (auto& kv : e->links) {
    if (kv.first == src_link) continue;
    ELink& lk = kv.second;
    if ((int64_t)lk.pamax.size() != e->L) {
      lk.pamax.resize((size_t)e->L);
      lk.pss.resize((size_t)e->L);
      lk.psabs.resize((size_t)e->L);
    }
    apply(lk.resid.data(), lk.resid.data(), lk.pamax.data(), lk.pss.data(),
          lk.psabs.data());
    lk.pvalid = true;
    lk.dirty = true;
  }
  if (e->has_carry)
    apply(e->carry.data(), e->carry.data(), nullptr, nullptr, nullptr);
  e->frames_in += applied;
  if (prec == 2) e->frames2_in += applied;
}

// apply_batch's r14 zero-repack twin: k frames applied STRAIGHT FROM the
// v3 wire body (per frame f: [scales L*4][words ...] at body + f*stride;
// the 24-byte aligned header guarantees the typed loads are legal). Same
// flood/carry/accounting semantics — only the repack copy is gone. The
// caller has already zeroed non-finite scales in place (the loaned rx
// buffer is process-local transport memory, safe to sanitize). Caller
// holds e->mu.
void apply_batch_wire(Engine* e, int32_t src_link, int32_t k,
                      const uint8_t* body, size_t stride, int prec)
    ST_REQUIRES(e->mu) {
  uint64_t applied = 0;
  for (int32_t f = 0; f < k; f++)
    if (any_nonzero((const float*)(body + (size_t)f * stride), e->L))
      applied++;
  if (applied == 0) return;
  auto apply = [&](const float* in, float* out, double* pa, double* ps,
                   double* pb) {
    if (prec == 2)
      stc_apply_frames2_wire(in, out, e->off.data(), e->ns.data(),
                             e->padded.data(), e->L, e->W, k, body,
                             (int64_t)stride, pa, ps, pb);
    else
      stc_apply_frames_wire(in, out, e->off.data(), e->ns.data(),
                            e->padded.data(), e->L, e->W, k, body,
                            (int64_t)stride, pa, ps, pb);
  };
  apply(e->values.data(), e->values.data(), nullptr, nullptr, nullptr);
  for (auto& kv : e->links) {
    if (kv.first == src_link) continue;
    ELink& lk = kv.second;
    if ((int64_t)lk.pamax.size() != e->L) {
      lk.pamax.resize((size_t)e->L);
      lk.pss.resize((size_t)e->L);
      lk.psabs.resize((size_t)e->L);
    }
    apply(lk.resid.data(), lk.resid.data(), lk.pamax.data(), lk.pss.data(),
          lk.psabs.data());
    lk.pvalid = true;
    lk.dirty = true;
  }
  if (e->has_carry)
    apply(e->carry.data(), e->carry.data(), nullptr, nullptr, nullptr);
  e->frames_in += applied;
  if (prec == 2) e->frames2_in += applied;
}

// ---- sender ---------------------------------------------------------------

size_t frame_bytes(const Engine* e) {
  return (size_t)e->L * 4 + (size_t)e->W * 4;
}

// Go-back-N retransmission pass (Engine::ack_timeout; the native twin of
// comm/peer.py _check_retransmit). For every live link whose oldest
// unacked message has waited past the timeout, resend the HEAD of the
// unacked tail BYTE-IDENTICAL — with the r07 slot ring that is literal:
// the ledger entry IS the wire bytes, so a retransmit is a new zero-copy
// reference on the same slot, never a re-encode. After ack_retry_limit
// fruitless rounds tear the link down (rollback -> dead -> drop) so
// LINK_DOWN -> carry -> re-graft recovers every undelivered frame on a
// fresh link instead of retrying forever.
void retransmit_pass(Engine* e, const std::vector<int32_t>& ids)
    ST_EXCLUDES(e->mu) {
  auto now = EClock::now();
  for (int32_t id : ids) {
    std::vector<TxSlot*> tail;
    bool teardown = false;
    {
      StLockGuard lk(e->mu);
      auto it = e->links.find(id);
      if (it == e->links.end() || it->second.dead) continue;
      ELink& lk2 = it->second;
      if (lk2.unacked.empty()) continue;
      double waited =
          std::chrono::duration<double>(now - lk2.ack_progress).count();
      // per-round exponential backoff, capped 8x (peer.py
      // _check_retransmit's twin): a flat timer would retransmit a
      // healthy-but-saturated link whose burst is still queued locally
      int32_t shift = lk2.retx_rounds < 3 ? lk2.retx_rounds : 3;
      if (waited < e->ack_timeout * (double)(1 << shift)) continue;
      lk2.retx_rounds++;
      lk2.ack_progress = now;
      if (lk2.retx_rounds > e->ack_retry_limit) {
        rollback_unacked(e, lk2);
        lk2.dead = true;
        teardown = true;
      } else {
        // head prefix only: O(kRetxPrefix) pointer grabs under e->mu (the
        // old path deep-copied the messages' frame vectors here), and
        // only the head can restore the receiver's in-order progress.
        // Each grabbed slot takes an in-flight reference NOW, under the
        // lock, so a racing ACK pop cannot recycle it mid-send.
        size_t k = lk2.unacked.size() < kRetxPrefix ? lk2.unacked.size()
                                                    : kRetxPrefix;
        for (size_t i = 0; i < k; i++) {
          TxSlot* s = lk2.unacked[i].slot;
          s->refs.fetch_add(1, std::memory_order_relaxed);
          tail.push_back(s);
        }
      }
    }
    if (teardown) {
      st_obs_emit(e->obs_id, kEvBlackhole, id, (uint64_t)e->ack_retry_limit);
      st_node_drop_link(e->node, id);
      continue;
    }
    if (!tail.empty()) {
      e->retx_msgs += (uint64_t)tail.size();
      st_obs_emit(e->obs_id, kEvRetransmit, id, (uint64_t)tail.size());
    }
    for (size_t i = 0; i < tail.size(); i++) {
      TxSlot* s = tail[i];
      int32_t r =
          st_node_send_zc(e->node, id, s->buf.data() + s->wire_off,
                          (int32_t)s->wire_len, 0.1, tx_slot_release, s);
      if (r != 1) {
        // not enqueued: the transport took no ownership — drop our
        // reference for this and every remaining tail slot, and let the
        // next pass (or LINK_DOWN) handle it
        for (size_t j = i; j < tail.size(); j++) e->txpool.unref(tail[j]);
        break;
      }
    }
  }
}

// One unledgered send with the same backpressure/quarantine discipline as
// the main path (r10 subscriber links). Returns false when the link died
// or was quarantined — the caller marks it dead and rolls its frames back.
bool sub_send(Engine* e, int32_t id, const uint8_t* p, size_t n) {
  int32_t fails = 0;
  while (!e->stop.load()) {
    int32_t r = st_node_send(e->node, id, p, (int32_t)n, 0.1);
    if (r == 1) return true;
    if (r < 0) return false;
    if (e->quarantine > 0 && ++fails >= e->quarantine) {
      st_obs_emit(e->obs_id, kEvQuarantine, id, (uint64_t)fails);
      st_node_drop_link(e->node, id);
      return false;
    }
  }
  return false;
}

void sender_loop(Engine* e) {
  std::vector<uint8_t> payload;
  std::vector<float> scales((size_t)e->L);
  std::vector<double> amax((size_t)e->L), ss((size_t)e->L),
      sabs((size_t)e->L);
  // r11 cascade schedule rows (frame-major, contiguous k*L — the kernels'
  // scale layout; the slot copies are per-frame)
  std::vector<float> sched((size_t)64 * e->L);
  const uint64_t gov_interval_ns =
      e->gov_interval > 0 ? (uint64_t)(e->gov_interval * 1e9) : 100000000ull;
  while (!e->stop.load()) {
    e->sender_pass.fetch_add(1);  // pass boundary (st_engine_pause sync)
    uint64_t seq_before;
    {
      StLockGuard lk(e->wmu);
      seq_before = e->wseq;
    }
    bool sent_any = false;
    std::vector<int32_t> ids;
    {
      StLockGuard lk(e->mu);
      for (auto& kv : e->links)
        if (!kv.second.dead) ids.push_back(kv.first);
    }
    // one clock read per pass feeds every link's governor beat (r11)
    uint64_t pass_ns = e->prec_mode == 1 ? st_obs_now_ns() : 0;
    for (int32_t id : ids) {
      if (e->stop.load()) return;
      SentMsg msg;
      TxSlot* slot = nullptr;
      size_t per = frame_bytes(e);
      int mprec = 1;  // this message's frame precision
      // r10 subscriber-link state, captured under e->mu for the unledgered
      // send path below (incl. the trace stamp — the ledgered path reads it
      // while packing headers under the same lock)
      bool sub = false, sub_ranged = false;
      int64_t sub_wlo = 0, sub_wcnt = 0;
      uint32_t tr_o = 0;
      uint64_t tr_g = 0;
      uint8_t tr_h = 0;
      {
        StLockGuard lk(e->mu);
        fold_pending(e);  // staged adds land before this link quantizes
        auto it = e->links.find(id);
        if (it == e->links.end() || it->second.dead) continue;
        ELink& lk2 = it->second;
        sub = lk2.subscriber;
        if (sub) {
          sub_ranged = lk2.ranged;
          sub_wlo = lk2.wlo;
          sub_wcnt = lk2.wcnt;
          if (lk2.fresh_interval_ns && !lk2.dirty) {
            // FRESH beat: the residual is fully drained — "as of now you
            // have everything I have, through message tx_seq" (the seq
            // makes the mark verifiable: a subscriber missing the stream
            // tail resyncs instead of falsely trusting it). Sent from
            // under e->mu with a zero timeout, same discipline as
            // flush_acks (lossy: a bounced beat retries next pass).
            uint64_t now = st_obs_now_ns();
            if (now - lk2.last_fresh_ns >= lk2.fresh_interval_ns) {
              uint8_t fb[13];
              fb[0] = kFresh;
              std::memcpy(fb + 1, &now, 8);
              uint32_t ls = (uint32_t)lk2.tx_seq;
              std::memcpy(fb + 9, &ls, 4);
              if (st_node_send(e->node, id, fb, 13, 0.0) == 1) {
                lk2.last_fresh_ns = now;
                e->sub_fresh_out++;
              }
            }
          }
        }
        // r11 precision governor — the first closed telemetry->data-plane
        // loop: the same per-link residual RMS the r09 st_residual_norm
        // gauge serves (the pss partials cache, O(L) under e->mu) drives
        // this link's wire precision. A link whose residual GROWS between
        // beats (rms > up_ratio * prev: the stream is falling behind the
        // mass arriving — chaos, retransmission storms, a stalled peer)
        // upshifts to the sign2 2-bit codec; one that drains fast or
        // quiesces (rms < down_ratio * prev, or zero) downshifts back. A
        // healthy saturated link (flat rms at equilibrium) stays 1-bit. Two consecutive
        // votes with reset-on-contrary give hysteresis so one noisy beat
        // can't flap the link. Emission stays gated on the peer's
        // advertised capability (kPrecBit note).
        if (e->prec_mode == 1 && !sub && !e->compat_bytes &&
            lk2.peer_sign2 &&
            pass_ns - lk2.gov_last_ns >= gov_interval_ns && lk2.pvalid) {
          double gss = 0;
          for (int64_t i = 0; i < e->L; i++) gss += lk2.pss[i];
          double rms = std::sqrt(gss / (double)e->total_n);
          // byte pressure harvested per beat (struct comment): sendq
          // bounces since the last beat, or a closed go-back-N window
          bool byte_bound = lk2.gov_bp > 0 || lk2.window_blocked;
          lk2.gov_bp = 0;
          lk2.gov_quiet = byte_bound ? 0 : lk2.gov_quiet + 1;
          if (lk2.gov_prev >= 0.0) {
            if (byte_bound && rms > 0 &&
                rms > lk2.gov_prev * e->gov_up_ratio) {
              // growing residual on a byte-bound link: the wire cannot
              // move the mass at 1 bit/element — the regime sign2's
              // per-byte advantage exists for
              lk2.gov_up++;
              lk2.gov_down = 0;
            } else if (rms <= 0 || rms < lk2.gov_prev * e->gov_down_ratio) {
              lk2.gov_down++;
              lk2.gov_up = 0;
            } else {
              lk2.gov_up = 0;
              lk2.gov_down = 0;
            }
            if (lk2.prec == 1 && lk2.gov_up >= 2) {
              lk2.prec = 2;
              lk2.gov_up = 0;
              e->prec_upshifts++;
              st_obs_emit(e->obs_id, kEvPrecShift, id, 2);
            } else if (lk2.prec == 2 &&
                       (lk2.gov_down >= 2 || lk2.gov_quiet >= kGovStall)) {
              // stand down when the residual quiesces (sign2 did its
              // job / the load vanished) or the byte-bound condition
              // lifts for kGovStall beats (bytes are no longer scarce —
              // the half-cost wire format moves the same frames)
              lk2.prec = 1;
              lk2.gov_down = 0;
              e->prec_downshifts++;
              st_obs_emit(e->obs_id, kEvPrecShift, id, 1);
            }
          }
          lk2.gov_prev = rms;
          lk2.gov_last_ns = pass_ns;
        }
        // r12 lifecycle quiesce: paused means no NEW production on any
        // link (the struct comment). Placed after the FRESH beat (which
        // only fires on a drained residual) and before the quantize path.
        // seq_cst load: st_engine_pause's pass-boundary handshake counts
        // on a pass that starts after the store observing it.
        if (e->paused.load()) continue;
        if (!lk2.dirty) continue;
        // go-back-N send window: a full unacked ledger (stalled peer)
        // stops NEW production on this link; the residual keeps
        // accumulating and quantizes once ACKs reopen the window — and,
        // with the ledger-as-slot design, bounds the live tx ring slots
        // per link at kSendWindow (the pool cannot grow past it)
        if (!e->compat_bytes && lk2.unacked.size() >= kSendWindow) {
          if (!lk2.window_blocked) {
            lk2.window_blocked = true;
            st_obs_emit(e->obs_id, kEvWindowStall, id,
                        (uint64_t)lk2.unacked.size());
          }
          continue;
        }
        lk2.window_blocked = false;
        // quantize up to `burst` successive halvings of the residual,
        // stopping at the first all-zero-scale frame (idle). EVERY quantize
        // pass accumulates the residual's scale partials fused
        // (stc_quantize_ef_partials) — one memory pass per frame instead of
        // quantize-then-rescan. Frame 0's partials come from the link's
        // cache when valid (refreshed by the fused add/flood passes), so
        // the standalone stc_scale_partials scan only runs after the rare
        // writes that bypass the fused kernels (rollback, restore) — at
        // 16 Mi / burst cap 1 that scan was a full 64 MiB read per message.
        //
        // r07 zero-copy: on the native framing the quantize target IS the
        // wire message — scales and sign words land at their final offsets
        // in a pooled tx slot (TxSlot layout), which then serves as ledger
        // entry, retransmission source, and scatter-gather send buffer
        // with no further copies.
        msg.nframes = 0;
        uint8_t* body = nullptr;
        if (!e->compat_bytes && !sub) {
          // subscriber links are unledgered: no slot (the ledger entry IS
          // the slot on the ledgered path) — frames quantize into the
          // msg.scales/words buffers like compat and encode below
          slot = e->txpool.acquire();
          body = slot->buf.data() + kBodyOff;
        }
        if ((int64_t)lk2.pamax.size() != e->L) {
          lk2.pamax.resize((size_t)e->L);
          lk2.pss.resize((size_t)e->L);
          lk2.psabs.resize((size_t)e->L);
          lk2.pvalid = false;
        }
        if (sub_ranged) {
          // range discipline: out-of-range residual is mass this link's
          // receiver will never get (adds/floods refill the FULL residual
          // between passes) — drop it BEFORE scale selection, so frames
          // never budget scale for it and the link goes idle the moment
          // its own pages drain (without this, the dropped mass decays
          // geometrically across dozens of frames of useless traffic)
          std::fill(lk2.resid.begin(), lk2.resid.begin() + sub_wlo * 32,
                    0.0f);
          std::fill(lk2.resid.begin() + (sub_wlo + sub_wcnt) * 32,
                    lk2.resid.end(), 0.0f);
          lk2.pvalid = false;  // cached partials counted the dropped mass
        }
        if (lk2.pvalid) {
          std::copy(lk2.pamax.begin(), lk2.pamax.end(), amax.begin());
          std::copy(lk2.pss.begin(), lk2.pss.end(), ss.begin());
          std::copy(lk2.psabs.begin(), lk2.psabs.end(), sabs.begin());
        } else {
          stc_scale_partials(lk2.resid.data(), e->off.data(), e->ns.data(),
                             e->L, amax.data(), ss.data(), sabs.data());
        }
        // r11: this message's precision, decided under e->mu. Ledgered
        // links only (sub/compat stay 1-bit: the serve tier's python
        // subscriber and the reference protocol don't speak sign2), and
        // only toward a peer that advertised decode capability.
        if (slot && lk2.peer_sign2 &&
            (e->prec_mode == 2 || (e->prec_mode == 1 && lk2.prec == 2)))
          mprec = 2;
        size_t fb = mprec == 2 ? per + (size_t)e->W * 4 : per;
        int bmax = sub && e->burst > kSubBurstCap ? kSubBurstCap : e->burst;
        if (mprec == 2) {
          // a sign2 burst is ~2x the bytes per frame: cap it so the
          // message still fits every peer's receive bound (r11
          // wire.frame_wire_bytes sized recv_cap for it)
          int64_t cap2 =
              ((int64_t)e->recv_cap - (int64_t)kHdrV3) / (int64_t)fb;
          if (cap2 < 1) cap2 = 1;
          if (bmax > cap2) bmax = (int)cap2;
        }
        if (slot) {
          // r11 cascade quantize: up to e->cascade halving frames per
          // MEMORY PASS (stcodec.c's r11 section). Frame 0's scales are
          // measured from the partials as before; frames 1..k-1 take the
          // halving schedule the measured sequence converges to anyway.
          // Scales ride the wire, so the receiver is oblivious; the
          // residual's drain per message gets DEEPER (bound ~s/2^(k-1))
          // while the sender's passes per message drop ~k-fold — the
          // pass count, not bandwidth, was the measured 1 Mi wall.
          int64_t wstride = (int64_t)(fb / 4);
          int kcmax = e->cascade < 1 ? 1 : (e->cascade > 64 ? 64 : e->cascade);
          while (msg.nframes < bmax) {
            scales_from_partials(e, amax, ss, sabs, scales.data());
            if (!any_nonzero(scales.data(), e->L)) {
              if (msg.nframes == 0) lk2.dirty = false;  // nothing to say
              break;
            }
            // Cascade schedule: per leaf, a pow2 ladder from the
            // residual's AMAX down to the policy (rms) scale. Anchoring
            // the top at amax (not rms) is what makes the drain
            // geometric for the WHOLE population: each |r| <= bound
            // level halves the bound, outliers included — an rms-anchored
            // ladder starves the gaussian tail (outliers move one
            // ever-shrinking +-s per frame; measured: amax decays
            // linearly and a full drain never terminates), while the
            // policy's own per-frame schedule has exactly the same tail
            // (it is the known slow-gaussian-tail regime). The depth
            // collapses to 1 on its own when pow2(amax) == policy scale
            // — the lockstep drain-tail states — and a single measured
            // frame then merges phase groups and terminates the drain
            // exactly (scale reads 0, link goes idle), the production
            // endgame. Measured on a 64 Ki gaussian: exact drain in 44
            // frames / 24 passes vs NO termination in 20 k frames for
            // the per-frame schedule. sign2's magnitude bit reaches 3s,
            // so its ladder starts two binades lower at equal coverage.
            int kc = 1;
            if (kcmax > 1) {
              int maxd = 1;
              for (int64_t i = 0; i < e->L; i++) {
                if (scales.data()[i] <= 0.0f) continue;
                union {
                  float f;
                  uint32_t u;
                } b;
                b.f = (float)amax[i];
                b.u &= 0x7F800000u;  // pow2 floor; subnormals -> 0
                float st = b.f;
                if (mprec == 2) st *= 0.25f;  // +-3s covers the top levels
                if (st <= scales.data()[i]) continue;
                int d = ilogbf(st) - ilogbf(scales.data()[i]) + 1;
                if (d > maxd) maxd = d;
              }
              // Dense states extend the ladder BELOW the rms anchor: the
              // extra refinement levels are nearly free in the same pass
              // and leave a cleaner (finer-lattice) residual, which the
              // endgame then merges in FEWER single-frame passes — the
              // measured 64 Ki gaussian drain goes 44 frames / 24 passes
              // (extra 0) -> 33 / 4 (extra 8), still terminating exactly.
              if (maxd > 1) maxd += 8;
              kc = maxd < kcmax ? maxd : kcmax;
            }
            if (kc > bmax - msg.nframes) kc = bmax - msg.nframes;
            int kreal = 0;
            for (int j = 0; j < kc; j++) {
              float* row = sched.data() + (size_t)j * e->L;
              if (j == 0) {
                if (kc == 1) {
                  // single measured frame: exactly the policy schedule
                  std::memcpy(row, scales.data(), (size_t)e->L * 4);
                } else {
                  for (int64_t i = 0; i < e->L; i++) {
                    float s = scales.data()[i];
                    if (s > 0.0f) {
                      union {
                        float f;
                        uint32_t u;
                      } b;
                      b.f = (float)amax[i];
                      b.u &= 0x7F800000u;
                      float st = b.f;
                      if (mprec == 2) st *= 0.25f;
                      if (st > s) s = st;  // ladder top (>= policy scale)
                    }
                    row[i] = s;
                  }
                }
              } else {
                const float* prev = sched.data() + (size_t)(j - 1) * e->L;
                for (int64_t i = 0; i < e->L; i++) row[i] = prev[i] * 0.5f;
                // the halving hit the denormal floor: an all-zero-scale
                // frame would count nowhere at the receiver (taxonomy)
                if (!any_nonzero(row, e->L)) break;
              }
              std::memcpy(body + (size_t)(msg.nframes + j) * fb, row,
                          (size_t)e->L * 4);
              kreal++;
            }
            uint8_t* f0 = body + (size_t)msg.nframes * fb;
            uint32_t* wbase = (uint32_t*)(f0 + (size_t)e->L * 4);
            if (mprec == 2)
              stc_quantize2_ef_cascade(
                  lk2.resid.data(), lk2.resid.data(), e->off.data(),
                  e->ns.data(), e->padded.data(), e->L, kreal, sched.data(),
                  wbase, wstride, e->W, amax.data(), ss.data(), sabs.data());
            else
              stc_quantize_ef_cascade(
                  lk2.resid.data(), lk2.resid.data(), e->off.data(),
                  e->ns.data(), e->padded.data(), e->L, kreal, sched.data(),
                  wbase, wstride, amax.data(), ss.data(), sabs.data());
            msg.nframes += kreal;
            if (kreal < kc) break;  // schedule floored mid-cascade
          }
        } else {
          for (int b = 0; b < bmax; b++) {
            scales_from_partials(e, amax, ss, sabs, scales.data());
            if (!any_nonzero(scales.data(), e->L)) {
              if (b == 0) lk2.dirty = false;  // nothing to say at all
              break;
            }
            size_t base_s = msg.scales.size(), base_w = msg.words.size();
            msg.scales.resize(base_s + (size_t)e->L);
            msg.words.resize(base_w + (size_t)e->W);
            float* fscales = msg.scales.data() + base_s;
            uint32_t* fwords = msg.words.data() + base_w;
            std::memcpy(fscales, scales.data(), (size_t)e->L * 4);
            stc_quantize_ef_partials(lk2.resid.data(), lk2.resid.data(),
                                     e->off.data(), e->ns.data(),
                                     e->padded.data(), e->L, scales.data(),
                                     fwords, amax.data(), ss.data(),
                                     sabs.data());
            msg.nframes++;
          }
        }
        // amax/ss/sabs now hold the post-quantize residual's partials
        // (whether any frame was emitted or not): seed the cache for the
        // next message.
        std::copy(amax.begin(), amax.end(), lk2.pamax.begin());
        std::copy(ss.begin(), ss.end(), lk2.pss.begin());
        std::copy(sabs.begin(), sabs.end(), lk2.psabs.begin());
        lk2.pvalid = true;
        if (msg.nframes == 0) {
          if (slot) e->txpool.unref(slot);
          continue;
        }
        e->frames_out += (uint64_t)msg.nframes;
        if (mprec == 2) e->frames2_out += (uint64_t)msg.nframes;
        msg.prec = (uint8_t)mprec;
        if (sub) {
          // unledgered: allocate wire seqs (the subscriber's gap detector
          // needs them) and capture the trace stamp; no unacked entry —
          // delivery degrades to ack-on-send like compat, and loss is the
          // subscriber's resync to repair
          int nmsg = sub_ranged ? msg.nframes : 1;
          msg.seq = lk2.tx_seq + 1;
          lk2.tx_seq += (uint64_t)nmsg;
          if (e->trace_wire) {
            tr_o = e->t_has ? e->t_origin : e->obs_id;
            tr_g = e->t_has ? e->t_gen : st_obs_now_ns();
            tr_h = e->t_has ? (uint8_t)(e->t_hops > 255 ? 255 : e->t_hops) : 0;
          }
        }
        // ledger entry BEFORE the send: the receiver's ACK must never race
        // ahead of the entry it acknowledges (comm/peer.py _send_loop).
        // Compat: no ACKs exist, so no ledger — delivery degrades to
        // ack-on-send like the Python compat tier (peer.py _send_loop
        // docstring); a failed send rolls back THIS message inline below.
        if (!e->compat_bytes && !sub) {
          msg.seq = ++lk2.tx_seq;
          // wire header, packed flush against the 8-aligned body at
          // kBodyOff (comm/wire.py framing; LE host assumed): BURST
          // [kind][u32 seq][u8 k], DATA [kind][u32 seq], each followed by
          // the 13-byte r09 trace context when trace_wire is on.
          uint32_t seq32 = (uint32_t)msg.seq;
          // r14: aligned v3 toward peers that advertised the capability
          // (24-byte header; trace context is a fixed field, so v3 also
          // requires trace emission — the ST_WIRE_TRACE=0 pin keeps v1)
          const bool v3 = lk2.wire_v3 && e->trace_wire;
          size_t hdr = v3 ? kHdrV3
                          : (e->burst > 1
                                 ? (e->trace_wire ? kBurstHdrV2 : kBurstHdrV1)
                                 : (e->trace_wire ? kDataHdrV2 : kDataHdrV1));
          slot->wire_off = (uint32_t)(kBodyOff - hdr);
          uint8_t* H = slot->buf.data() + slot->wire_off;
          size_t o;
          // r11: the kind byte's top bit marks sign2 frame bodies (see
          // kPrecBit) — set only toward peers that advertised the decode
          uint8_t pbit = mprec == 2 ? kPrecBit : 0;
          if (v3) {
            std::memset(H, 0, kHdrV3);
            H[0] = (e->burst > 1 ? kBurst : kData) | pbit;
            H[1] = (uint8_t)msg.nframes;
            std::memcpy(H + 4, &seq32, 4);
            uint32_t to = e->t_has ? e->t_origin : e->obs_id;
            uint64_t tg = e->t_has ? e->t_gen : st_obs_now_ns();
            uint8_t th =
                e->t_has ? (uint8_t)(e->t_hops > 255 ? 255 : e->t_hops) : 0;
            std::memcpy(H + 8, &to, 4);
            std::memcpy(H + 12, &tg, 8);
            H[20] = th;
          } else {
          if (e->burst > 1) {
            H[0] = kBurst | pbit;
            std::memcpy(H + 1, &seq32, 4);
            H[5] = (uint8_t)msg.nframes;
            o = kBurstHdrV1;
          } else {
            H[0] = kData | pbit;
            std::memcpy(H + 1, &seq32, 4);
            o = kDataHdrV1;
          }
          if (e->trace_wire) {
            // pending stamp, read under e->mu (we hold it here). A node
            // that never added nor applied anything traced stamps itself
            // at hop 0 — e.g. the join-seed diff residual.
            uint32_t to = e->t_has ? e->t_origin : e->obs_id;
            uint64_t tg = e->t_has ? e->t_gen : st_obs_now_ns();
            uint8_t th =
                e->t_has ? (uint8_t)(e->t_hops > 255 ? 255 : e->t_hops) : 0;
            std::memcpy(H + o, &to, 4);
            std::memcpy(H + o + 4, &tg, 8);
            H[o + 12] = th;
          }
          }
          slot->wire_len =
              (uint32_t)(hdr + (size_t)msg.nframes * fb);
          msg.slot = slot;  // the ledger entry owns the acquire reference
          msg.sent_at = EClock::now();
          if (lk2.unacked.empty()) lk2.ack_progress = msg.sent_at;
          it->second.unacked.push_back(msg);
          // in-flight reference for the send below, taken UNDER e->mu:
          // after the lock drops, a concurrent detach/stash_carry can
          // rollback_unacked and drop the ledger reference — taken
          // outside the lock, the slot could hit zero refs and be
          // recycled before our send even starts (use-after-free read +
          // a double free-list push). retransmit_pass refs under the
          // lock for the same reason.
          slot->refs.fetch_add(1, std::memory_order_relaxed);
        }
      }
      // r10 subscriber links: encode + send outside the lock, unledgered.
      // Ranged: one kRData message per frame ([kind][seq][wlo][wcnt]
      // [trace?][scales][word slice]) — the subscriber receives and
      // buffers ONLY its pages. Full-table: one ordinary DATA/BURST
      // message (the subscriber speaks the normal framing, just without
      // ACKing it). Frame buffers live in msg.scales/words (transient —
      // nothing to retransmit, by design).
      if (sub) {
        st_fault_crash_point("mid-burst");
        const size_t L4 = (size_t)e->L * 4;
        bool ok = true;
        if (sub_ranged) {
          const size_t hdr = e->trace_wire ? 26 : 13;
          payload.resize(hdr + L4 + (size_t)sub_wcnt * 4);
          for (int32_t f = 0; f < msg.nframes && ok; f++) {
            uint8_t* p = payload.data();
            p[0] = kRData;
            uint32_t s32 = (uint32_t)(msg.seq + (uint64_t)f);
            uint32_t lo32 = (uint32_t)sub_wlo, c32 = (uint32_t)sub_wcnt;
            std::memcpy(p + 1, &s32, 4);
            std::memcpy(p + 5, &lo32, 4);
            std::memcpy(p + 9, &c32, 4);
            size_t o = 13;
            if (e->trace_wire) {
              std::memcpy(p + o, &tr_o, 4);
              std::memcpy(p + o + 4, &tr_g, 8);
              p[o + 12] = tr_h;
              o += 13;
            }
            std::memcpy(p + o, msg.scales.data() + (size_t)f * e->L, L4);
            std::memcpy(p + o + L4,
                        msg.words.data() + (size_t)f * e->W + sub_wlo,
                        (size_t)sub_wcnt * 4);
            ok = sub_send(e, id, payload.data(), payload.size());
            if (ok) e->sub_msgs_out++;
          }
        } else {
          const size_t per2 = L4 + (size_t)e->W * 4;
          const bool burst = msg.nframes > 1;
          const size_t hdr =
              burst ? (e->trace_wire ? kBurstHdrV2 : kBurstHdrV1)
                    : (e->trace_wire ? kDataHdrV2 : kDataHdrV1);
          payload.resize(hdr + (size_t)msg.nframes * per2);
          uint8_t* p = payload.data();
          uint32_t s32 = (uint32_t)msg.seq;
          size_t o;
          if (burst) {
            p[0] = kBurst;
            std::memcpy(p + 1, &s32, 4);
            p[5] = (uint8_t)msg.nframes;
            o = kBurstHdrV1;
          } else {
            p[0] = kData;
            std::memcpy(p + 1, &s32, 4);
            o = kDataHdrV1;
          }
          if (e->trace_wire) {
            std::memcpy(p + o, &tr_o, 4);
            std::memcpy(p + o + 4, &tr_g, 8);
            p[o + 12] = tr_h;
            o += 13;
          }
          for (int32_t f = 0; f < msg.nframes; f++) {
            std::memcpy(p + o, msg.scales.data() + (size_t)f * e->L, L4);
            std::memcpy(p + o + L4, msg.words.data() + (size_t)f * e->W,
                        (size_t)e->W * 4);
            o += per2;
          }
          ok = sub_send(e, id, payload.data(), payload.size());
          if (ok) e->sub_msgs_out++;
        }
        if (ok) {
          sent_any = true;
        } else {
          // undelivered: roll this message's frames back so a detach
          // returns the residual the subscriber is still owed, and mark
          // the link dead (membership is Python's call, as everywhere)
          StLockGuard lk(e->mu);
          auto it = e->links.find(id);
          if (it != e->links.end()) {
            for (int32_t f = 0; f < msg.nframes; f++)
              stc_apply_frame(it->second.resid.data(),
                              it->second.resid.data(), e->off.data(),
                              e->ns.data(), e->padded.data(), e->L,
                              msg.scales.data() + (size_t)f * e->L,
                              msg.words.data() + (size_t)f * e->W);
            it->second.pvalid = false;
            it->second.dead = true;
          }
        }
        continue;
      }
      // send outside the lock
      if (e->compat_bytes) {
        // reference raw frames, nframes of them back-to-back (see the
        // compat-burst note in st_engine_create): each is
        // [f32 scale][ceil(n/8) mask bytes]; L == 1 (the peer rejects
        // multi-leaf tables in compat mode) and ceil(n/8) <= W*4, so the
        // words buffer always covers each mask
        payload.resize((size_t)msg.nframes * e->compat_bytes);
        for (int32_t f = 0; f < msg.nframes; f++) {
          uint8_t* p = payload.data() + (size_t)f * e->compat_bytes;
          std::memcpy(p, msg.scales.data() + (size_t)f * e->L, 4);
          std::memcpy(p + 4, msg.words.data() + (size_t)f * e->W,
                      (size_t)e->compat_bytes - 4);
        }
      }
      // crash point: frames quantized + error feedback applied + ledger
      // entry pushed, message NOT yet on the wire — death here must roll
      // the whole burst into the re-graft carry on restart
      st_fault_crash_point("mid-burst");
      bool delivered = false;
      int32_t fails = 0, bounces = 0;
      // (the in-flight slot reference for this send was taken under e->mu
      // at ledger-push time — see above)
      while (!e->stop.load()) {
        int32_t r =
            slot ? st_node_send_zc(e->node, id,
                                   slot->buf.data() + slot->wire_off,
                                   (int32_t)slot->wire_len, 0.1,
                                   tx_slot_release, slot)
                 : st_node_send(e->node, id, payload.data(),
                                (int32_t)payload.size(), 0.1);
        if (r == 1) {
          delivered = true;
          break;
        }
        if (r < 0) break;  // dead link
        bounces++;  // sat out the full timeout on a full sendq
        if (e->quarantine > 0 && ++fails >= e->quarantine) {
          // quarantine: tear the stalled link down; the failed-send
          // rollback below + Python's LINK_DOWN -> carry -> re-graft
          // recover every undelivered frame
          st_obs_emit(e->obs_id, kEvQuarantine, id, (uint64_t)fails);
          st_node_drop_link(e->node, id);
          break;
        }
      }
      if (slot && !delivered)
        e->txpool.unref(slot);  // transport took no ownership
      if (bounces > 0 && e->prec_mode == 1) {
        // byte backpressure observed: feed the precision governor's
        // byte-bound gate (harvested at its next beat)
        StLockGuard lk(e->mu);
        auto it = e->links.find(id);
        if (it != e->links.end()) it->second.gov_bp += (uint32_t)bounces;
      }
      if (delivered) {
        // compat: every frame IS a protocol message (the reference wire has
        // no message framing beyond the fixed frame size), keeping the
        // taxonomy's msgs == frames on both ends of a compat link
        e->msgs_out += e->compat_bytes ? (uint64_t)msg.nframes : 1;
        sent_any = true;
      } else {
        // undelivered: roll ALL outstanding feedback back so a re-graft
        // owes the full residual (peer.py nack path on send failure).
        // Compat has no ledger — roll back this message's own frames
        // directly (stronger than the reference, which loses them).
        StLockGuard lk(e->mu);
        auto it = e->links.find(id);
        if (it != e->links.end()) {
          if (e->compat_bytes) {
            for (int32_t f = 0; f < msg.nframes; f++)
              stc_apply_frame(it->second.resid.data(),
                              it->second.resid.data(), e->off.data(),
                              e->ns.data(), e->padded.data(), e->L,
                              msg.scales.data() + (size_t)f * e->L,
                              msg.words.data() + (size_t)f * e->W);
            it->second.pvalid = false;  // inline rollback bypasses the cache
          } else {
            rollback_unacked(e, it->second);
          }
          it->second.dead = true;
        }
      }
    }
    // go-back-N delivery timer: retransmit stranded unacked tails (and
    // tear down black-hole links) — runs every pass, dirty links or not
    if (!e->compat_bytes && e->ack_timeout > 0 && !e->stop.load())
      retransmit_pass(e, ids);
    if (!sent_any && !e->stop.load()) {
      // explicit wait loop (not wait_for-with-predicate): the predicate
      // lambda would read the wmu-guarded wseq from a context the
      // thread-safety analysis treats as lock-free
      StUniqueLock lk(e->wmu);
      auto nap_deadline = st_cv_deadline(0.05);
      while (e->wseq <= seq_before && !e->stop.load()) {
        if (e->wcv.wait_until(lk.native(), nap_deadline) ==
            std::cv_status::timeout)
          break;
      }
    }
  }
}

// ---- receiver -------------------------------------------------------------

void flush_acks(Engine* e, int32_t id, ELink& lk) ST_REQUIRES(e->mu) {
  // cumulative + retried (a backpressure-dropped ACK must be re-offered or
  // the sender's ledger never drains — comm/peer.py _flush_acks)
  if (e->compat_bytes) return;  // the reference protocol has no ACKs
  if (lk.rx_count <= lk.ack_sent || lk.dead) return;
  uint8_t ack[9];
  ack[0] = kAck;
  uint64_t c = lk.rx_count;
  std::memcpy(ack + 1, &c, 8);  // little-endian host assumed (x86/arm64-le)
  int32_t r = st_node_send(e->node, id, ack, 9, 0.0);
  if (r == 1 || r < 0) lk.ack_sent = lk.rx_count;
}

void receiver_loop(Engine* e) {
  // batch accumulators (frames from one link applied in one pass)
  std::vector<float> bscales;
  std::vector<uint32_t> bwords;
  size_t per = frame_bytes(e);
  while (!e->stop.load()) {
    uint64_t seq0 = st_node_data_seq(e->node);
    bool busy = false;
    std::vector<int32_t> ids;
    {
      StLockGuard lk(e->mu);
      for (auto& kv : e->links)
        if (!kv.second.dead) ids.push_back(kv.first);
    }
    // r09 trace bookkeeping is part of the obs subsystem's toggleable cost
    // (the overhead bench's paired A/B flips this flag): when off, traced
    // headers are still parsed for framing but no clock reads / atomics /
    // events happen per message.
    bool obs_on = st_obs_is_enabled() != 0;
    for (int32_t id : ids) {
      int32_t batchk = 0;
      int batch_prec = 1;  // r11: a batch is precision-homogeneous
      // r14 zero-repack path: a v3 message pending direct-from-wire apply
      // (the pointers borrow the current recv_zc loan, so it flushes
      // before the next pop)
      const uint8_t* wire_body = nullptr;
      int32_t wire_k = 0;
      int wire_prec = 1;
      size_t wire_stride = 0;
      uint64_t msgs = 0;
      // last traced stamp accepted in this batch (+ per-batch aggregates):
      // folded into the engine's pending stamp and the link's staleness
      // gauge at flush, under e->mu
      bool have_trace = false;
      uint32_t tr_origin = 0, tr_hops = 0;
      uint64_t tr_gen = 0;
      uint64_t n_traced = 0, hops_acc = 0;
      // last in-order wire seq accepted on this link (go-back-N; only this
      // thread advances rx_count, so the snapshot stays valid across the
      // batch — msgs tracks acceptances not yet folded in by flush)
      uint64_t rx_base = 0;
      {
        StLockGuard lk(e->mu);
        auto it = e->links.find(id);
        if (it != e->links.end()) rx_base = it->second.rx_count;
      }
      bscales.clear();
      bwords.clear();
      auto flush = [&]() {
        if (batchk == 0 && msgs == 0 && wire_k == 0) return;
        StLockGuard lk(e->mu);
        auto it = e->links.find(id);
        if (it == e->links.end()) return;
        if (batchk > 0) {
          apply_batch(e, id, batchk, bscales.data(), bwords.data(),
                      batch_prec);
        }
        if (wire_k > 0) {
          apply_batch_wire(e, id, wire_k, wire_body, wire_stride, wire_prec);
          wire_k = 0;
          wire_body = nullptr;
        }
        if (have_trace) {
          // advance the pending stamp: this node is now one hop further
          // from the origin than the message that carried it
          uint32_t hop = tr_hops + 1;
          e->t_origin = tr_origin;
          e->t_gen = tr_gen;
          e->t_hops = hop;
          e->t_has = true;
          if (obs_on) {
            uint64_t now = st_obs_now_ns();
            uint64_t age = now > tr_gen ? now - tr_gen : 0;
            it->second.stale_ns = age;
            it->second.last_hops = hop;
            e->staleness_ns_last.store(age, std::memory_order_relaxed);
            e->hops_sum += hops_acc;
            e->hops_msgs += n_traced;
            e->traced_msgs_in += n_traced;
          }
          have_trace = false;
          n_traced = 0;
          hops_acc = 0;
        }
        // crash point: applied + flooded, ACK not yet sent — the sender
        // still ledgers these messages and re-delivers (at-least-once)
        if (msgs > 0) st_fault_crash_point("between-apply-and-ack");
        it->second.rx_count += msgs;
        e->msgs_in += msgs;
        rx_base += msgs;
        flush_acks(e, id, it->second);
        batchk = 0;
        msgs = 0;
        bscales.clear();
        bwords.clear();
      };
      for (int iter = 0; iter < 256; iter++) {  // bounded: don't starve links
        // r11: also bound the batch by FRAMES. flush() applies the whole
        // batch in one fused pass under e->mu and only THEN acks — at
        // saturation (256 messages x a ~31-frame burst each) that single
        // flush runs for whole seconds, the peer's send window (32 msgs)
        // stays exhausted the entire time, and the stream freezes into a
        // stop-and-go duty cycle paced by our flush latency. 256 frames
        // keeps the fused pass in the tens-of-ms class (both precisions)
        // so the cumulative ACK advances continuously and the sender's
        // window never starves; the table read still amortizes across
        // the full batch.
        if (batchk >= 256) break;
        // r14: zero-copy pop — `buf` borrows the transport's rx buffer
        // until the next recv_zc/recv_done on this link; everything this
        // iteration needs is either parsed or copied (batch vectors,
        // ctrl queue) before the next pop releases it
        const uint8_t* buf = nullptr;
        int32_t n = st_node_recv_zc(e->node, id, &buf, 0.0);
        if (n == 0) break;
        if (n < 0) {
          // dead + drained; rollback happens at detach (or the sender's
          // failed send) — membership/carry is Python's call
          StLockGuard lk(e->mu);
          auto it = e->links.find(id);
          if (it != e->links.end()) it->second.dead = true;
          break;
        }
        busy = true;
        if (e->compat_bytes) {
          // raw reference frame: [f32 scale][mask bytes], fixed size (the
          // transport's compat framing delivers whole frames only).
          // scale == 0 is the reference's idle keepalive (quirk Q2) and
          // non-finite is corruption (quirk Q9) — both are no-ops that
          // count nowhere, keeping msgs == frames (the compat exception in
          // peer.metrics()'s taxonomy).
          if ((size_t)n != (size_t)e->compat_bytes || e->sealed.load())
            continue;
          float sc;
          std::memcpy(&sc, buf, 4);
          if (sc == 0.0f || !std::isfinite(sc)) continue;
          msgs++;
          size_t bs = bscales.size(), bw = bwords.size();
          bscales.resize(bs + (size_t)e->L);  // L == 1 in compat
          bwords.resize(bw + (size_t)e->W, 0u);
          bscales[bs] = sc;
          std::memcpy(bwords.data() + bw, buf + 4,
                      (size_t)e->compat_bytes - 4);
          batchk++;
          continue;
        }
        uint8_t kind = buf[0];
        // r11 precision bit: data kinds may carry kPrecBit marking sign2
        // (2-bit) frame bodies — decoded unconditionally (tolerant decode;
        // EMISSION is what capability-gates). Any other kind with the top
        // bit set stays an unknown control message.
        int p2 = 0;
        if ((kind & kPrecBit) &&
            ((kind & ~kPrecBit) == kData || (kind & ~kPrecBit) == kBurst)) {
          p2 = 1;
          kind &= ~kPrecBit;
        }
        if (kind == kData || kind == kBurst) {
          if (e->sealed.load()) continue;  // leaving: sender re-delivers
          // Go-back-N acceptance (comm/wire.py tx_seq): only the next
          // in-order, DECODABLE message is applied and counted. A
          // duplicate (seq <= rx: injected, or a retransmit racing our
          // ACK) and anything after a gap (seq > rx+1: a message vanished
          // at the wire) are discarded unapplied; an undecodable message
          // (truncated/garbled) likewise does NOT consume its seq — the
          // sender's retransmission re-delivers it whole, and our
          // cumulative ACK is always exactly the last accepted seq.
          if (n < 5) continue;  // too short to carry a seq: undecodable
          // v1/v2/v3 framing by exact length (per_rx is a multiple of 4;
          // 5/18 for kData, 6/19 for kBurst, 24 for v3 — all distinct
          // mod 4, so the sizes can never coincide): any sender's
          // messages keep applying on any node (the version gates are
          // about what we EMIT). The r11 precision bit selects the frame
          // width FIRST (per vs per+4W), so the discriminations compose.
          // v3 must be detected BEFORE the seq check — its seq field
          // lives at byte 4, not 1.
          size_t per_rx = p2 ? per + (size_t)e->W * 4 : per;
          const bool v3 = (size_t)n >= kHdrV3 && buf[1] > 0 &&
                          (size_t)n == kHdrV3 + (size_t)buf[1] * per_rx;
          uint32_t seq;
          std::memcpy(&seq, buf + (v3 ? 4 : 1), 4);
          if (seq != (uint32_t)(rx_base + msgs + 1)) {  // dup/gap: discard
            e->dedup_discards++;
            st_obs_emit(e->obs_id, kEvDedupDiscard, id, (uint64_t)seq);
            continue;
          }
          int32_t k = 0;
          const uint8_t* p = nullptr;
          const uint8_t* trace = nullptr;  // 13-byte context, if present
          if (v3) {
            k = buf[1];
            trace = buf + 8;  // [origin u32][gen u64][hops u8], v2 order
            p = buf + kHdrV3;
          } else if (kind == kData && (size_t)n == kDataHdrV1 + per_rx) {
            k = 1;
            p = buf + kDataHdrV1;
          } else if (kind == kData && (size_t)n == kDataHdrV2 + per_rx) {
            k = 1;
            trace = buf + kDataHdrV1;
            p = buf + kDataHdrV2;
          } else if (kind == kBurst && n >= 6 && buf[5] > 0 &&
                     (size_t)n == kBurstHdrV1 + (size_t)buf[5] * per_rx) {
            k = buf[5];
            p = buf + kBurstHdrV1;
          } else if (kind == kBurst && n >= 19 && buf[5] > 0 &&
                     (size_t)n == kBurstHdrV2 + (size_t)buf[5] * per_rx) {
            k = buf[5];
            trace = buf + kBurstHdrV1;
            p = buf + kBurstHdrV2;
          } else {
            continue;  // undecodable: seq not consumed, await retransmit
          }
          // r14 zero-repack routing: the direct-from-wire apply flushes
          // PER MESSAGE (its pointers borrow the recv_zc loan), which
          // forfeits the cross-message batch amortization — a pure loss
          // on small tables where the per-pass table walk is cheap and
          // clumped messages are common. Route v3 messages to the direct
          // path only when the repack copy it deletes is the bigger cost
          // (>= 1 MiB of wire body); below that they join the ordinary
          // batch, whose per-frame parse handles the v3 body layout
          // identically (p already points past the 24-byte header).
          const bool direct =
              v3 && (size_t)k * per_rx >= (size_t)(1 << 20);
          // a precision change flushes the pending batch (apply_batch is
          // homogeneous); rx_base tracking spans the flush safely
          if (batchk > 0 && (direct || batch_prec != (p2 ? 2 : 1))) flush();
          batch_prec = p2 ? 2 : 1;
          msgs++;
          if (trace) {
            std::memcpy(&tr_origin, trace, 4);
            std::memcpy(&tr_gen, trace + 4, 8);
            tr_hops = trace[12];
            have_trace = true;
            if (obs_on) {
              uint32_t hop = tr_hops + 1;
              n_traced++;
              hops_acc += hop;
              // one record per accepted traced message: node/link say who
              // applied it, arg carries the generation (origin ns), extra
              // packs (origin id << 8 | hop) — the flight recorder
              // reconstructs the full causal path from these
              // (obs/trace_export.py trace_paths).
              st_obs_emit2(e->obs_id, kEvTraceApply, id, tr_gen,
                           (tr_origin << 8) | (hop > 255 ? 255 : hop));
            }
          }
          if (direct) {
            // r14 zero-repack apply: the 24-byte header 8-aligns the
            // body, so the fused kernels read scales/words straight from
            // the loaned wire buffer — no per-frame memcpy into batch
            // vectors at all. Sanitize non-finite scales IN PLACE first
            // (trust boundary; the loan is our own transport memory),
            // then flush immediately: the borrowed pointers must not
            // outlive this message's loan (released by the next pop).
            for (int32_t f = 0; f < k; f++) {
              float* s = const_cast<float*>(
                  reinterpret_cast<const float*>(p + (size_t)f * per_rx));
              for (int64_t i = 0; i < e->L; i++)
                if (!std::isfinite(s[i])) s[i] = 0.0f;
            }
            wire_body = p;
            wire_k = k;
            wire_prec = p2 ? 2 : 1;
            wire_stride = per_rx;
            flush();
            continue;
          }
          size_t wk = p2 ? (size_t)e->W * 2 : (size_t)e->W;  // words/frame
          for (int32_t f = 0; f < k; f++) {
            size_t bs = bscales.size(), bw = bwords.size();
            bscales.resize(bs + (size_t)e->L);
            bwords.resize(bw + wk);
            std::memcpy(bscales.data() + bs, p, (size_t)e->L * 4);
            p += (size_t)e->L * 4;
            std::memcpy(bwords.data() + bw, p, wk * 4);
            p += wk * 4;
            // trust boundary: non-finite scales become no-op leaves
            // (wire.decode_frame guard; quirk Q9's receive-path analog)
            for (int64_t i = 0; i < e->L; i++) {
              float* s = bscales.data() + bs + i;
              if (!std::isfinite(*s)) *s = 0.0f;
            }
            batchk++;
          }
        } else if (kind == kAck && n == 9) {
          uint64_t count;
          std::memcpy(&count, buf + 1, 8);
          StLockGuard lk(e->mu);
          auto it = e->links.find(id);
          if (it != e->links.end()) {
            ELink& lk2 = it->second;
            lk2.acked_cum = count;
            // cumulative ACK = last in-order wire seq the peer accepted;
            // every ledger entry at or below it is delivered — its tx slot
            // drops the ledger reference and returns to the ring once any
            // in-flight (re)send reference drains too
            bool progressed = false;
            auto ack_at = EClock::now();
            while (!lk2.unacked.empty() && lk2.unacked.front().seq <= count) {
              SentMsg& m = lk2.unacked.front();
              // delivery round trip: ledger append -> cumulative-ACK pop
              e->rtt_ns_total += (uint64_t)std::chrono::duration_cast<
                                     std::chrono::nanoseconds>(
                                     ack_at - m.sent_at)
                                     .count();
              e->rtt_msgs++;
              if (m.slot) e->txpool.unref(m.slot);
              lk2.unacked.pop_front();
              progressed = true;
            }
            if (progressed) {
              // delivery progressed: reset the go-back-N timer
              lk2.ack_progress = EClock::now();
              lk2.retx_rounds = 0;
            }
          }
        } else {
          // control-plane message (handshake retries, REJECT, unknown):
          // preserve ordering — flush data first — then hand to Python
          flush();
          StLockGuard lk(e->cmu);
          e->ctrl.emplace_back(
              id, std::vector<uint8_t>(buf, buf + n));
        }
      }
      bool applied = batchk > 0;
      flush();
      // the last loaned rx buffer is fully parsed/copied by now
      st_node_recv_done(e->node, id);
      {
        // retry any previously-backpressured ACK even on idle passes
        StLockGuard lk(e->mu);
        auto it = e->links.find(id);
        if (it != e->links.end()) flush_acks(e, id, it->second);
      }
      if (applied) e->wake();  // flood refilled other links' residuals
    }
    if (!busy && !e->stop.load()) {
      st_node_wait_data(e->node, seq0, 0.05);
    }
  }
}

// ---- r17 engine-tier shard data plane -------------------------------------
//
// The r16 shard FWD plane (shared_tensor_tpu/shard/node.py) ran entirely in
// Python — correctness-first, ~3 orders of interpreter cost per message
// above the classic plane's native engine. This section ports the HOT LOOP
// into the same machinery: outbox residuals quantize DIRECTLY into
// refcounted TxSlots as burst-packed FWD frames (error feedback per target
// shard, the successive-halving drain ladder per message), relays forward a
// FWD whose owner is downstream VERBATIM — the received buffer's ownership
// transfers via st_node_recv_take, only the per-link seq is re-stamped in
// place, and the same bytes enqueue zero-copy through st_node_send_zc
// (sendmmsg/shm-lane eligible) while serving as the go-back-N ledger entry
// — and the owner's (origin, fwd_seq) dedup + slice apply commit under ONE
// plane mutex, byte-compatible with the Python tier's dedup windows so
// checkpoints and mixed trees interop.
//
// The CONTROL plane stays in Python (claim/grant/handoff/arbitration/
// announces): every non-FWD/ACK message on a member link defers to the
// ctrl queue (st_shard_poll_ctrl), exactly the engine/peer.py split.
// Ownership/routing mutations arrive over the ABI (adopt/release/
// set_route/set_handoff), all under the same mutex as the data path.
//
// Parity discipline: slice_quantize/slice_apply mirror state.SliceCodec
// step for step (same f32 elementwise arithmetic, double accumulation for
// the scale reductions — state.py accumulates in f64 too, so POW2_RMS
// scales agree bit-for-bit in practice and scales always ride the wire).
// tests/test_shard_engine.py pins byte-equal frames/residuals/applies on
// shared random state via the exported st_slice_quantize/st_slice_apply.

constexpr uint8_t kFwd = 17;      // comm/wire.py FWD
constexpr size_t kFwdHdr = 21;    // [kind][seq u32][wlo u32][wcnt u32]
                                  // [origin u32][fwd_seq u32]
constexpr size_t kShardDedupWindow = 1024;  // shard/node.py DEDUP_WINDOW
constexpr int kOutboxMsgsPerPass = 4;  // shard/node.py OUTBOX_MSGS_PER_PASS
constexpr int32_t kCtrlHeadroom = 3;   // shard/node.py _queue_room keep
constexpr uint32_t kEvShardParkDrop = 36;  // obs/events.py CODE_NAMES
constexpr uint32_t kEvShardDedup = 37;

struct ShardSeg {
  int64_t g;       // global leaf index
  int64_t i0, i1;  // slice-element bounds of the segment
  int64_t n_live;  // non-padding elements in it
};

// Per-shard slice geometry, precomputed once at create (the shard ranges
// are fixed at creation — the r16 contract the python ShardMap carries).
struct ShardGeom {
  int64_t wlo = 0, wcnt = 0, elo = 0, n_el = 0;
  std::vector<ShardSeg> segs;
  std::vector<int32_t> leaf_of;  // slice element -> global leaf
  std::vector<float> live;       // 1.0 live / 0.0 padding
  int32_t kcap = 1;              // FWD frames per message (recv bound)
  // SYNTHETIC LAYOUT (r17): each segment presented as a leaf of a
  // slice-local table — live elements are a contiguous prefix of every
  // segment and segment bounds are 32-multiples, so the slice is a
  // legal stcodec layout and the hot loops ride the SAME AVX-512
  // cascade/apply kernels as the classic plane (stc_quantize_ef_cascade
  // / stc_apply_frames) instead of scalar twins.
  std::vector<int64_t> syn_off, syn_ns, syn_padded;
  std::vector<int32_t> syn_g;  // synthetic leaf -> global leaf
};

// One received FWD buffer whose ownership transferred from the transport
// (st_node_recv_take): refcounted like a TxSlot — the go-back-N ledger
// holds one reference, each in-flight (re)send another. The LAST unref
// returns the buffer to the transport's rx pool. `plane_live` lets
// st_shard_destroy wait for stragglers exactly like the TxPool drain.
struct ShardPlane;
struct TakenBuf {
  ShardPlane* plane = nullptr;
  void* tok = nullptr;
  uint8_t* data = nullptr;
  uint32_t len = 0;
  int32_t from_link = 0;
  std::atomic<int32_t> refs{0};
};

struct ShardSent {
  uint64_t seq = 0;
  TxSlot* slot = nullptr;    // originated / re-packed copy
  TakenBuf* taken = nullptr; // zero-copy relay
};

struct SMember {
  std::deque<ShardSent> unacked;
  uint64_t tx_seq = 0, rx_count = 0, ack_sent = 0;
  bool ack_due = false;
  EClock::time_point ack_progress{};
  int32_t retx_rounds = 0;
  bool window_blocked = false;
  bool dead = false;
  // per-link send-order mutex: the outbox pump (sender thread) and the
  // verbatim relay (receiver thread) both produce ledgered FWDs on the
  // same link — holding this across [seq alloc + ledger push + transport
  // enqueue] keeps wire order = seq order, which the python tier gets
  // for free from its single loop thread. Lock order: order_mu -> mu.
  std::shared_ptr<StMutex> order_mu = std::make_shared<StMutex>();
};

struct ParkedFwd {
  int32_t shard = 0;
  std::vector<uint8_t> bytes;
};

struct ShardPlane {
  void* node = nullptr;
  uint32_t obs_id = 0, origin = 0;
  int64_t L = 0, total = 0, total_n = 0, W = 0;
  std::vector<int64_t> off, ns, padded;
  int policy = kPow2Rms;
  int32_t recv_cap = 0;
  double ack_timeout = 0.0;
  int32_t ack_retry_limit = 8;
  int32_t park_cap = 4096;
  std::vector<ShardGeom> geom;  // n_shards entries, fixed at create

  TxPool txpool;

  StMutex mu;
  std::map<int32_t, std::vector<float>> owned ST_GUARDED_BY(mu);
  std::map<int32_t, std::vector<float>> outbox ST_GUARDED_BY(mu);
  std::set<int32_t> ho_sent ST_GUARDED_BY(mu);
  std::map<int32_t, SMember> members ST_GUARDED_BY(mu);
  std::map<int32_t, int32_t> route ST_GUARDED_BY(mu);
  int32_t uplink ST_GUARDED_BY(mu) = -1;
  uint32_t fwd_seq ST_GUARDED_BY(mu) = 0;
  // origin -> (seen set, insertion fifo): the end-to-end dedup window,
  // byte-compatible with shard/node.py's (DEDUP_WINDOW trim included)
  std::map<uint32_t, std::pair<std::set<uint32_t>, std::deque<uint32_t>>>
      dedup ST_GUARDED_BY(mu);
  std::deque<ParkedFwd> parked ST_GUARDED_BY(mu);

  // control messages (non FWD/ACK on member links) surfaced to Python
  StMutex cmu;
  std::deque<std::pair<int32_t, std::vector<uint8_t>>> ctrl
      ST_GUARDED_BY(cmu);

  // sender wake (missed-wakeup-safe sequence counter)
  StMutex wmu;
  std::condition_variable wcv;
  uint64_t wseq ST_GUARDED_BY(wmu) = 0;

  std::atomic<bool> stop{false};
  std::atomic<uint64_t> fwd_msgs_out{0}, fwd_msgs_in{0}, relayed{0};
  std::atomic<uint64_t> dedup_discards{0}, park_drops{0}, retx_msgs{0};
  std::atomic<uint64_t> updates{0}, fwd_frames_out{0}, fwd_frames_in{0};
  std::atomic<uint64_t> fwd_undecodable{0};
  std::atomic<int64_t> taken_live{0};
  std::thread send_thread, recv_thread;
  bool started = false;

  void wake() ST_EXCLUDES(wmu) {
    {
      StLockGuard lk(wmu);
      wseq++;
    }
    wcv.notify_all();
  }
};

void shard_geom_init(ShardPlane* p, const int64_t* wlo, const int64_t* wcnt,
                     int32_t n_shards) {
  // leaf boundaries: element index where each leaf's padded span ends
  std::vector<int64_t> bounds((size_t)p->L);
  int64_t acc = 0;
  for (int64_t i = 0; i < p->L; i++) {
    acc += p->padded[(size_t)i];
    bounds[(size_t)i] = acc;
  }
  p->geom.resize((size_t)n_shards);
  for (int32_t s = 0; s < n_shards; s++) {
    ShardGeom& g = p->geom[(size_t)s];
    g.wlo = wlo[s];
    g.wcnt = wcnt[s];
    g.elo = g.wlo * 32;
    g.n_el = g.wcnt * 32;
    g.leaf_of.resize((size_t)g.n_el);
    g.live.resize((size_t)g.n_el);
    int64_t leaf = 0;
    while (leaf < p->L && bounds[(size_t)leaf] <= g.elo) leaf++;
    for (int64_t j = 0; j < g.n_el; j++) {
      int64_t el = g.elo + j;
      while (leaf < p->L && bounds[(size_t)leaf] <= el) leaf++;
      int64_t lf = leaf < p->L ? leaf : p->L - 1;
      g.leaf_of[(size_t)j] = (int32_t)lf;
      g.live[(size_t)j] =
          (el - p->off[(size_t)lf]) < p->ns[(size_t)lf] ? 1.0f : 0.0f;
    }
    // contiguous runs of one leaf -> segments with live counts
    int64_t i0 = 0;
    while (i0 < g.n_el) {
      int64_t i1 = i0;
      int32_t lf = g.leaf_of[(size_t)i0];
      int64_t nl = 0;
      while (i1 < g.n_el && g.leaf_of[(size_t)i1] == lf) {
        if (g.live[(size_t)i1] != 0.0f) nl++;
        i1++;
      }
      g.segs.push_back(ShardSeg{lf, i0, i1, nl});
      g.syn_off.push_back(i0);
      g.syn_ns.push_back(nl);
      g.syn_padded.push_back(i1 - i0);
      g.syn_g.push_back(lf);
      i0 = i1;
    }
    size_t per = (size_t)p->L * 4 + (size_t)g.wcnt * 4;
    int64_t cap = ((int64_t)p->recv_cap - (int64_t)kFwdHdr) / (int64_t)per;
    if (cap < 1) cap = 1;
    if (cap > 255) cap = 255;
    g.kcap = (int32_t)cap;
  }
}

// The slice-codec hot loops below carry the plane's whole per-byte cost
// (quantize on the writer, apply at the owner): O3 + vectorization for
// just these bodies — exact float semantics, NO fast-math (the parity
// contract). Guarded off clang: the analyze gate runs -Werror and clang
// warns on gcc optimize pragmas it cannot honor.
#ifndef __clang__
#pragma GCC push_options
#pragma GCC optimize("O3,tree-vectorize")
#endif

// Per-segment scale measurement (state.SliceCodec.measure): scales per
// GLOBAL leaf (zero outside the range) + per-leaf amax. Reductions
// accumulate EXACT f64 products (f32->f64 squares are exact, so only the
// accumulation order is inexact) with 8 interleaved accumulators — a
// FIXED deterministic order; state.py's f64 numpy sum (pairwise) agrees
// with it to the last bit after the f32 cast in practice, which the
// parity test pins on shared random state.
//
// Layout note the speed leans on: within one leaf, LIVE elements are a
// contiguous prefix (padding sits at the leaf tail), so every segment
// splits into [live prefix | padding tail] and the per-element
// scale/live lookups collapse to constants per span.
void slice_measure(const ShardPlane* p, const ShardGeom& g,
                   const float* resid, float* scales, float* amaxes) {
  std::memset(scales, 0, (size_t)p->L * 4);
  std::memset(amaxes, 0, (size_t)p->L * 4);
  for (const ShardSeg& sg : g.segs) {
    if (sg.n_live <= 0) continue;
    int64_t live_end = sg.i0 + sg.n_live;
    // amax over the segment's elements (padding is 0 and cannot win; a
    // NaN element falls out of the comparisons here, and then poisons
    // the sum below into scales[g] = 0 — the same skipped segment
    // numpy's NaN-propagating max produces)
    float m0 = 0, m1 = 0, m2 = 0, m3 = 0;
    int64_t j = sg.i0;
    for (; j + 4 <= sg.i1; j += 4) {
      float b0 = std::fabs(resid[j]), b1 = std::fabs(resid[j + 1]);
      float b2 = std::fabs(resid[j + 2]), b3 = std::fabs(resid[j + 3]);
      if (b0 > m0) m0 = b0;
      if (b1 > m1) m1 = b1;
      if (b2 > m2) m2 = b2;
      if (b3 > m3) m3 = b3;
    }
    for (; j < sg.i1; j++) {
      float a = std::fabs(resid[j]);
      if (a > m0) m0 = a;
    }
    float am = m0;
    if (m1 > am) am = m1;
    if (m2 > am) am = m2;
    if (m3 > am) am = m3;
    if (!(am > 0.0f) || !std::isfinite(am)) continue;
    amaxes[sg.g] = am;
    double a0 = 0, a1 = 0, a2 = 0, a3 = 0, a4 = 0, a5 = 0, a6 = 0, a7 = 0;
    float s;
    if (p->policy == kAbsMean) {
      j = sg.i0;
      for (; j + 8 <= live_end; j += 8) {
        a0 += std::fabs((double)resid[j]);
        a1 += std::fabs((double)resid[j + 1]);
        a2 += std::fabs((double)resid[j + 2]);
        a3 += std::fabs((double)resid[j + 3]);
        a4 += std::fabs((double)resid[j + 4]);
        a5 += std::fabs((double)resid[j + 5]);
        a6 += std::fabs((double)resid[j + 6]);
        a7 += std::fabs((double)resid[j + 7]);
      }
      for (; j < live_end; j++) a0 += std::fabs((double)resid[j]);
      double acc = ((a0 + a1) + (a2 + a3)) + ((a4 + a5) + (a6 + a7));
      s = (float)(acc / (double)(float)sg.n_live);
    } else {
      j = sg.i0;
      for (; j + 8 <= live_end; j += 8) {
        double d0 = resid[j], d1 = resid[j + 1];
        double d2 = resid[j + 2], d3 = resid[j + 3];
        double d4 = resid[j + 4], d5 = resid[j + 5];
        double d6 = resid[j + 6], d7 = resid[j + 7];
        a0 += d0 * d0;
        a1 += d1 * d1;
        a2 += d2 * d2;
        a3 += d3 * d3;
        a4 += d4 * d4;
        a5 += d5 * d5;
        a6 += d6 * d6;
        a7 += d7 * d7;
      }
      for (; j < live_end; j++) {
        double d = resid[j];
        a0 += d * d;
      }
      double acc = ((a0 + a1) + (a2 + a3)) + ((a4 + a5) + (a6 + a7));
      s = (float)std::sqrt(acc / (double)(float)sg.n_live);
      if (p->policy == kPow2Rms) {
        union {
          float f;
          uint32_t u;
        } b;
        b.f = s;
        b.u &= 0x7F800000u;  // 2^floor(log2 s); subnormals -> 0
        s = b.f;
      }
    }
    scales[sg.g] = std::isfinite(s) ? s : 0.0f;
  }
}

// Pack + error-feedback one frame at a GIVEN scale row (the cascade
// rung) — state.SliceCodec.quantize_at. EF per segment with a constant
// scale over the live prefix (on the pre-subtraction sign), padding
// tail pinned to exactly 0 (the `new_r *= live` twin). The cold-path
// scalar twin of the stc cascade kernels the pump rides.
void slice_quantize_at(const ShardPlane* p, const ShardGeom& g,
                       float* resid, const float* row, uint32_t* words) {
  (void)p;
  // sign plane: bit j = (resid[j] <= 0) on LIVE lanes, 0 on padding —
  // the stcodec cascade-kernel convention (receivers mask by live)
  for (int64_t w = 0; w < g.wcnt; w++) {
    uint32_t bits = 0;
    const float* r = resid + w * 32;
    const float* lv = g.live.data() + w * 32;
    for (int b = 0; b < 32; b++)
      bits |= (uint32_t)(r[b] <= 0.0f && lv[b] != 0.0f) << b;
    words[w] = bits;
  }
  for (const ShardSeg& sg : g.segs) {
    float se = sg.n_live > 0 ? row[sg.g] : 0.0f;
    int64_t live_end = sg.i0 + sg.n_live;
    if (se > 0.0f) {
      for (int64_t k = sg.i0; k < live_end; k++) {
        float r0 = resid[k];
        resid[k] = r0 <= 0.0f ? r0 + se : r0 - se;
      }
    }
    for (int64_t k = live_end; k < sg.i1; k++) resid[k] = 0.0f;
  }
}

inline uint32_t f32_exp(float x) {
  uint32_t u;
  std::memcpy(&u, &x, 4);
  return (u >> 23) & 0xFFu;
}

bool slice_row_any(const ShardPlane* p, const float* row) {
  for (int64_t i = 0; i < p->L; i++)
    if (row[i] != 0.0f) return true;
  return false;
}

// Message-build scratch (one per sender thread / test call).
struct ShardScratch {
  std::vector<float> mscales, row, sched;
  std::vector<double> dpart;
};

// Build one FWD message's frames into `body` at wire strides (frame f's
// GLOBAL scale row at f*per, its word plane at f*per + 4L): ONE
// measurement (stc_scale_partials over the synthetic slice layout), the
// cascade-halving schedule (amax-anchored frame 0, +1 binade per rung to
// the measured scale, +8 refinement rungs — state.SliceCodec.cascade_rows
// bit-for-bit: the exponent math is integer), then every word plane in
// ONE memory pass via the classic plane's AVX-512 cascade kernel
// (stc_quantize_ef_cascade). Returns the frame count (0 = idle; the
// residual is then untouched). Error feedback lands in `resid` in place.
int slice_cascade_message(const ShardPlane* p, const ShardGeom& g,
                          float* resid, int kmax, uint8_t* body, size_t per,
                          ShardScratch& scr) {
  size_t nsyn = g.syn_g.size();
  if (scr.mscales.size() < nsyn) {
    scr.mscales.resize(nsyn);
    scr.row.resize(nsyn);
  }
  if (scr.dpart.size() < nsyn * 3) scr.dpart.resize(nsyn * 3);
  double* pa = scr.dpart.data();
  double* ps = pa + nsyn;
  double* pb = ps + nsyn;
  stc_scale_partials(resid, g.syn_off.data(), g.syn_ns.data(),
                     (int64_t)nsyn, pa, ps, pb);
  int d = 0;
  bool anyscale = false;
  for (size_t i = 0; i < nsyn; i++) {
    double n_live = (double)(float)g.syn_ns[i];
    float s = 0.0f;
    if (pa[i] > 0 && std::isfinite(pa[i]) && n_live > 0) {
      if (p->policy == kAbsMean) {
        s = (float)(pb[i] / n_live);
      } else {
        s = (float)std::sqrt(ps[i] / n_live);
        if (p->policy == kPow2Rms) {
          union {
            float f;
            uint32_t u;
          } b;
          b.f = s;
          b.u &= 0x7F800000u;
          s = b.f;
        }
      }
      if (!std::isfinite(s)) s = 0.0f;
    }
    scr.mscales[i] = s;
    if (s > 0.0f) {
      anyscale = true;
      union {
        float f;
        uint32_t u;
      } b;
      b.f = (float)pa[i];
      b.u &= 0x7F800000u;
      float top = b.f;
      int di = (int)f32_exp(top) - (int)f32_exp(s);
      if (di > d) d = di;
      scr.row[i] = top > s ? top : s;
    } else {
      scr.row[i] = 0.0f;
    }
  }
  if (!anyscale) return 0;
  int kc = d + 1 + (d > 0 ? 8 : 0);
  if (kc > kmax) kc = kmax;
  if (kc > 64) kc = 64;  // the cascade kernel's schedule cap
  if (kc < 1) kc = 1;
  if (scr.sched.size() < (size_t)kc * nsyn)
    scr.sched.resize((size_t)kc * nsyn);
  int nf = 0;
  for (int f = 0; f < kc; f++) {
    bool anyrow = false;
    for (size_t i = 0; i < nsyn; i++) {
      float v =
          f == 0 ? scr.row[i] : scr.sched[(size_t)(f - 1) * nsyn + i] * 0.5f;
      scr.sched[(size_t)f * nsyn + i] = v;
      if (v != 0.0f) anyrow = true;
    }
    if (f > 0 && !anyrow) break;  // halved into the subnormal floor
    nf++;
  }
  uint32_t* wbase = (uint32_t*)(body + (size_t)p->L * 4);
  stc_quantize_ef_cascade(resid, resid, g.syn_off.data(), g.syn_ns.data(),
                          g.syn_padded.data(), (int64_t)nsyn, nf,
                          scr.sched.data(), wbase, (int64_t)(per / 4), pa,
                          ps, pb);
  // scatter each rung's synthetic scales into the wire's GLOBAL per-leaf
  // rows (zero outside the slice's leaves)
  for (int f = 0; f < nf; f++) {
    float* sc = (float*)(body + (size_t)f * per);
    std::memset(sc, 0, (size_t)p->L * 4);
    for (size_t i = 0; i < nsyn; i++)
      sc[g.syn_g[i]] = scr.sched[(size_t)f * nsyn + i];
  }
  return nf;
}

// One measured single-frame step (state.SliceCodec.quantize — the
// serve-tier shape and the st_slice_quantize parity surface).
bool slice_quantize(const ShardPlane* p, const ShardGeom& g, float* resid,
                    float* scales, uint32_t* words) {
  std::vector<float> amaxes((size_t)p->L);
  slice_measure(p, g, resid, scales, amaxes.data());
  if (!slice_row_any(p, scales)) return false;
  slice_quantize_at(p, g, resid, scales, words);
  return true;
}

// Receiver step (state.SliceCodec.apply): target += scale[leaf]*(1-2*bit)
// on live lanes, saturated at +/-kSat. False for an all-zero-scale no-op.
// Same segment structure as the quantize: constant scale per live
// prefix; the padding tail only pays the clip (a no-op for the 0-valued
// padding an owned slice maintains — byte-identical to numpy's
// whole-slice np.clip).
bool slice_apply(const ShardPlane* p, const ShardGeom& g, float* target,
                 const float* scales, const uint32_t* words) {
  bool any = false;
  for (int64_t i = 0; i < p->L; i++)
    if (scales[i] != 0.0f) any = true;
  if (!any) return false;
  for (const ShardSeg& sg : g.segs) {
    float se = sg.n_live > 0 ? scales[sg.g] : 0.0f;
    int64_t live_end = sg.i0 + sg.n_live;
    for (int64_t j = sg.i0; j < live_end; j++) {
      float bf = (float)((words[j >> 5] >> (j & 31)) & 1u);
      float t = target[j] + se * (1.0f - 2.0f * bf);
      if (t > kSat) t = kSat;
      if (t < -kSat) t = -kSat;
      target[j] = t;
    }
    for (int64_t j = live_end; j < sg.i1; j++) {
      float t = target[j];
      if (t > kSat) t = kSat;
      if (t < -kSat) t = -kSat;
      target[j] = t;
    }
  }
  return true;
}

#ifndef __clang__
#pragma GCC pop_options
#endif

int32_t shard_of_word(const ShardPlane* p, uint32_t word_lo) {
  for (size_t s = 0; s < p->geom.size(); s++)
    if ((int64_t)word_lo >= p->geom[s].wlo &&
        (int64_t)word_lo < p->geom[s].wlo + p->geom[s].wcnt)
      return (int32_t)s;
  return -1;
}

void taken_unref(TakenBuf* t) {
  if (t->refs.fetch_sub(1, std::memory_order_acq_rel) == 1) {
    ShardPlane* p = t->plane;
    st_node_take_free(p->node, t->from_link, t->tok);
    delete t;
    p->taken_live.fetch_sub(1, std::memory_order_acq_rel);
  }
}

void taken_release(void* ctx) { taken_unref((TakenBuf*)ctx); }

void shard_entry_unref(ShardPlane* p, ShardSent& e) {
  if (e.slot) p->txpool.unref(e.slot);
  if (e.taken) taken_unref(e.taken);
  e.slot = nullptr;
  e.taken = nullptr;
}

// shard -> next-hop link (shard/node.py _next_hop): the learned route,
// else the uplink; never the arrival link, never a dead member. Caller
// holds p->mu.
int32_t shard_next_hop(ShardPlane* p, int32_t shard, int32_t arrival)
    ST_REQUIRES(p->mu) {
  auto rit = p->route.find(shard);
  if (rit != p->route.end() && rit->second != arrival) {
    auto mit = p->members.find(rit->second);
    if (mit != p->members.end() && !mit->second.dead) return rit->second;
  }
  if (p->uplink >= 0 && p->uplink != arrival) {
    auto mit = p->members.find(p->uplink);
    if (mit != p->members.end() && !mit->second.dead) return p->uplink;
  }
  return -1;
}

void shard_park(ShardPlane* p, int32_t shard, const uint8_t* data,
                uint32_t len) ST_REQUIRES(p->mu) {
  p->parked.push_back(ParkedFwd{shard, std::vector<uint8_t>(data, data + len)});
  while ((int32_t)p->parked.size() > p->park_cap) {
    p->parked.pop_front();
    // loud bounded loss, never unbounded memory (ShardConfig.park_cap)
    p->park_drops++;
    st_obs_emit(p->obs_id, kEvShardParkDrop, 0, 0);
  }
}

// Ledger + send one FWD on a member link, preserving per-link wire order
// across the two producing threads (see SMember::order_mu). The entry's
// bytes are re-stamped in place with the link's next seq. Consumes ONE
// owned reference of slot/taken on success (the ledger keeps it); takes
// its own in-flight reference for the transport enqueue. False = member
// gone/dead or go-back-N window full — ownership NOT consumed.
bool shard_ledger_send(ShardPlane* p, int32_t link, TxSlot* slot,
                       TakenBuf* taken, uint8_t* data, uint32_t len)
    ST_EXCLUDES(p->mu) {
  std::shared_ptr<StMutex> omu;
  {
    StLockGuard lk(p->mu);
    auto it = p->members.find(link);
    if (it == p->members.end() || it->second.dead) return false;
    omu = it->second.order_mu;
  }
  StLockGuard ol(*omu);
  {
    StLockGuard lk(p->mu);
    auto it = p->members.find(link);
    if (it == p->members.end() || it->second.dead) return false;
    SMember& m = it->second;
    if (m.unacked.size() >= kSendWindow) {
      if (!m.window_blocked) {
        m.window_blocked = true;
        st_obs_emit(p->obs_id, kEvWindowStall, link,
                    (uint64_t)m.unacked.size());
      }
      return false;
    }
    m.window_blocked = false;
    uint64_t seq = ++m.tx_seq;
    uint32_t s32 = (uint32_t)seq;
    std::memcpy(data + 1, &s32, 4);  // re-stamp ONLY the per-link seq
    if (m.unacked.empty()) m.ack_progress = EClock::now();
    ShardSent ent;
    ent.seq = seq;
    ent.slot = slot;
    ent.taken = taken;
    m.unacked.push_back(ent);
    // in-flight reference for the send below, taken under p->mu (the
    // TxPool r07 rationale: a racing ACK/detach may drop the ledger
    // reference the moment the lock releases)
    if (slot) slot->refs.fetch_add(1, std::memory_order_relaxed);
    if (taken) taken->refs.fetch_add(1, std::memory_order_relaxed);
  }
  int32_t r = st_node_send_zc(p->node, link, data, (int32_t)len, 0.05,
                              slot ? tx_slot_release : taken_release,
                              slot ? (void*)slot : (void*)taken);
  if (r != 1) {
    // bounced/dead: the transport took no ownership — drop the in-flight
    // reference; the entry stays ledgered and go-back-N re-sends it
    if (slot) p->txpool.unref(slot);
    if (taken) taken_unref(taken);
  }
  return true;
}

// Owner-side apply with end-to-end dedup: the (origin, fwd_seq) window
// check/insert and the slice apply commit together under p->mu — the
// same one-mutex discipline node.py's _apply_fwd/_dedup_mu carries, so a
// checkpoint capture under the same mutex can never persist a window seq
// whose mass missed the slice. Caller holds p->mu and has verified
// ownership. Returns true (the message is consumed either way).
bool shard_apply_fwd(ShardPlane* p, int32_t shard, uint8_t* data,
                     uint32_t len, std::vector<float>& sscratch,
                     std::vector<uint32_t>& wscratch) ST_REQUIRES(p->mu) {
  const ShardGeom& g = p->geom[(size_t)shard];
  uint32_t wlo, wcnt, origin, fseq;
  std::memcpy(&wlo, data + 5, 4);
  std::memcpy(&wcnt, data + 9, 4);
  std::memcpy(&origin, data + 13, 4);
  std::memcpy(&fseq, data + 17, 4);
  size_t per = (size_t)p->L * 4 + (size_t)g.wcnt * 4;
  int64_t body = (int64_t)len - (int64_t)kFwdHdr;
  int64_t nf = per > 0 ? body / (int64_t)per : 0;
  if ((int64_t)wlo != g.wlo || (int64_t)wcnt != g.wcnt || body <= 0 ||
      body % (int64_t)per != 0 || nf < 1 || nf > 255) {
    // relays forward verbatim without decoding, so a frame a fault
    // corrupted upstream is first DECODED here at the owner — drop it
    // loudly instead of poisoning the slice (node.py's decode guard)
    p->fwd_undecodable++;
    return true;
  }
  auto& win = p->dedup[origin];
  if (win.first.count(fseq)) {
    p->dedup_discards++;
    st_obs_emit(p->obs_id, kEvShardDedup, 0, (uint64_t)fseq);
    return true;
  }
  win.first.insert(fseq);
  win.second.push_back(fseq);
  while (win.second.size() > kShardDedupWindow) {
    win.first.erase(win.second.front());
    win.second.pop_front();
  }
  auto oit = p->owned.find(shard);
  float* vals = oit->second.data();
  // the 21-byte header leaves the frame body 1 (mod 4): gather every
  // frame's scales (global leaf rows -> synthetic slice rows) and words
  // into aligned scratch — the relay path, which never decodes, is what
  // stays zero-copy — sanitizing non-finite scales at the trust
  // boundary (wire.decode_fwd's twin), then apply the WHOLE burst in
  // one fused pass over the synthetic layout (stc_apply_frames, the
  // classic receive kernel).
  size_t nsyn = g.syn_g.size();
  if (sscratch.size() < (size_t)nf * nsyn)
    sscratch.resize((size_t)nf * nsyn);
  if (wscratch.size() < (size_t)(nf * g.wcnt))
    wscratch.resize((size_t)(nf * g.wcnt));
  uint64_t frames = 0;
  float sv;
  for (int64_t f = 0; f < nf; f++) {
    const uint8_t* fp = data + kFwdHdr + (size_t)f * per;
    bool anyf = false;
    for (size_t i = 0; i < nsyn; i++) {
      std::memcpy(&sv, fp + (size_t)g.syn_g[i] * 4, 4);
      if (!std::isfinite(sv)) sv = 0.0f;
      sscratch[(size_t)f * nsyn + i] = sv;
      if (sv != 0.0f) anyf = true;
    }
    if (anyf) frames++;
    std::memcpy(wscratch.data() + (size_t)f * g.wcnt,
                fp + (size_t)p->L * 4, (size_t)g.wcnt * 4);
  }
  if (frames > 0) {
    stc_apply_frames(vals, vals, g.syn_off.data(), g.syn_ns.data(),
                     g.syn_padded.data(), (int64_t)nsyn, g.wcnt,
                     (int32_t)nf, sscratch.data(), wscratch.data(), nullptr,
                     nullptr, nullptr);
    p->fwd_msgs_in++;
    p->fwd_frames_in += frames;
  }
  return true;
}

// Apply locally (owner), relay toward the owner, or return false (the
// caller parks). `slot`/`taken`/`data` carry the message exactly like
// shard_ledger_send; on a true return the passed reference is consumed.
// arrival = -1 for re-dispatch (link death / unpark) — which, per the
// r16 discipline, re-routes under the UNCHANGED end-to-end identity so a
// delivered-but-unacked copy dies in the owner's dedup window.
bool shard_dispatch(ShardPlane* p, int32_t shard, TxSlot* slot,
                    TakenBuf* taken, uint8_t* data, uint32_t len,
                    int32_t arrival, std::vector<float>& sscratch,
                    std::vector<uint32_t>& wscratch) ST_EXCLUDES(p->mu) {
  int32_t hop = -1;
  {
    StLockGuard lk(p->mu);
    if (p->owned.count(shard) && !p->ho_sent.count(shard)) {
      shard_apply_fwd(p, shard, data, len, sscratch, wscratch);
      if (slot) p->txpool.unref(slot);
      if (taken) taken_unref(taken);
      return true;
    }
    hop = shard_next_hop(p, shard, arrival);
  }
  if (hop < 0) return false;
  if (!shard_ledger_send(p, hop, slot, taken, data, len)) return false;
  if (arrival >= 0) p->relayed++;
  return true;
}

// Re-dispatch a parked/rolled-back FWD held as plain bytes: re-pack into
// a fresh tx slot (the original buffer is gone) and dispatch. False =
// still routeless (caller re-parks the bytes).
bool shard_dispatch_bytes(ShardPlane* p, int32_t shard,
                          const std::vector<uint8_t>& bytes,
                          std::vector<float>& sscratch,
                          std::vector<uint32_t>& wscratch)
    ST_EXCLUDES(p->mu) {
  {
    // owner fast path: no slot needed
    StLockGuard lk(p->mu);
    if (p->owned.count(shard) && !p->ho_sent.count(shard)) {
      shard_apply_fwd(p, shard, const_cast<uint8_t*>(bytes.data()),
                      (uint32_t)bytes.size(), sscratch, wscratch);
      return true;
    }
    if (shard_next_hop(p, shard, -1) < 0) return false;
  }
  TxSlot* slot = p->txpool.acquire();
  uint32_t off = (uint32_t)(kBodyOff - kFwdHdr);
  std::memcpy(slot->buf.data() + off, bytes.data(), bytes.size());
  slot->wire_off = off;
  slot->wire_len = (uint32_t)bytes.size();
  if (!shard_dispatch(p, shard, slot, nullptr, slot->buf.data() + off,
                      (uint32_t)bytes.size(), -1, sscratch, wscratch)) {
    p->txpool.unref(slot);
    return false;
  }
  return true;
}

// Go-back-N retransmission pass (the engine retransmit_pass twin, minus
// rollback: FWD ledger entries re-dispatch at detach instead of rolling
// back into a residual). Black-hole links tear down via st_node_drop_link
// — Python's LINK_DOWN handler detaches and re-routes the ledger.
void shard_retransmit(ShardPlane* p) ST_EXCLUDES(p->mu) {
  if (p->ack_timeout <= 0) return;
  auto now = EClock::now();
  std::vector<int32_t> ids;
  {
    StLockGuard lk(p->mu);
    for (auto& kv : p->members)
      if (!kv.second.dead) ids.push_back(kv.first);
  }
  for (int32_t id : ids) {
    std::vector<std::pair<const uint8_t*, uint32_t>> tail;
    std::vector<ShardSent> held;
    bool teardown = false;
    {
      StLockGuard lk(p->mu);
      auto it = p->members.find(id);
      if (it == p->members.end() || it->second.dead) continue;
      SMember& m = it->second;
      if (m.unacked.empty()) continue;
      double waited =
          std::chrono::duration<double>(now - m.ack_progress).count();
      int32_t shift = m.retx_rounds < 3 ? m.retx_rounds : 3;
      if (waited < p->ack_timeout * (double)(1 << shift)) continue;
      m.retx_rounds++;
      m.ack_progress = now;
      if (m.retx_rounds > p->ack_retry_limit) {
        m.dead = true;
        teardown = true;
      } else {
        size_t k = m.unacked.size() < kRetxPrefix ? m.unacked.size()
                                                  : kRetxPrefix;
        for (size_t i = 0; i < k; i++) {
          ShardSent& e = m.unacked[i];
          const uint8_t* d;
          uint32_t n;
          if (e.slot) {
            e.slot->refs.fetch_add(1, std::memory_order_relaxed);
            d = e.slot->buf.data() + e.slot->wire_off;
            n = e.slot->wire_len;
          } else {
            e.taken->refs.fetch_add(1, std::memory_order_relaxed);
            d = e.taken->data;
            n = e.taken->len;
          }
          tail.emplace_back(d, n);
          held.push_back(e);
        }
      }
    }
    if (teardown) {
      st_obs_emit(p->obs_id, kEvBlackhole, id, (uint64_t)p->ack_retry_limit);
      st_node_drop_link(p->node, id);
      continue;
    }
    if (!tail.empty()) {
      p->retx_msgs += (uint64_t)tail.size();
      st_obs_emit(p->obs_id, kEvRetransmit, id, (uint64_t)tail.size());
    }
    for (size_t i = 0; i < tail.size(); i++) {
      ShardSent& e = held[i];
      int32_t r = st_node_send_zc(
          p->node, id, tail[i].first, (int32_t)tail[i].second, 0.1,
          e.slot ? tx_slot_release : taken_release,
          e.slot ? (void*)e.slot : (void*)e.taken);
      if (r != 1) {
        for (size_t j = i; j < held.size(); j++)
          shard_entry_unref(p, held[j]);
        break;
      }
    }
  }
}

void shard_flush_acks(ShardPlane* p, int32_t id, SMember& m)
    ST_REQUIRES(p->mu) {
  // cumulative + retried + RE-ANNOUNCED on duplicates (node.py: a dup
  // usually means our ACK was lost — a sender whose retransmissions are
  // silently discarded without a fresh ACK is wedged forever)
  if (!m.ack_due || m.dead) return;
  uint8_t ack[9];
  ack[0] = kAck;
  uint64_t c = m.rx_count;
  std::memcpy(ack + 1, &c, 8);
  int32_t r = st_node_send(p->node, id, ack, 9, 0.0);
  if (r == 1 || r < 0) {
    m.ack_due = false;
    m.ack_sent = m.rx_count;
  }
}

void shard_unpark(ShardPlane* p, std::vector<float>& sscratch,
                  std::vector<uint32_t>& wscratch) ST_EXCLUDES(p->mu) {
  std::deque<ParkedFwd> work;
  {
    StLockGuard lk(p->mu);
    if (p->parked.empty()) return;
    work.swap(p->parked);
  }
  for (auto& pf : work) {
    if (!shard_dispatch_bytes(p, pf.shard, pf.bytes, sscratch, wscratch)) {
      StLockGuard lk(p->mu);
      shard_park(p, pf.shard, pf.bytes.data(), (uint32_t)pf.bytes.size());
    }
  }
}

// ---- shard sender: the outbox pump ----------------------------------------

void shard_sender_loop(ShardPlane* p) {
  std::vector<float> sscratch;
  std::vector<uint32_t> wscratch;
  ShardScratch scr;
  while (!p->stop.load()) {
    uint64_t seq_before;
    {
      StLockGuard lk(p->wmu);
      seq_before = p->wseq;
    }
    bool sent_any = false;
    bool blocked = false;  // work exists but the queue/window gated it
    std::vector<int32_t> shards;
    {
      StLockGuard lk(p->mu);
      for (auto& kv : p->outbox)
        if (!p->owned.count(kv.first)) shards.push_back(kv.first);
    }
    for (int32_t shard : shards) {
      if (p->stop.load()) return;
      int32_t hop;
      {
        StLockGuard lk(p->mu);
        hop = shard_next_hop(p, shard, -1);
      }
      if (hop < 0) continue;  // mass stays until a route heals
      // control-traffic headroom (node.py _queue_room): the pump must
      // never race the ACKs/shard control for the last sendq slots
      if (st_node_sendq_room(p->node, hop) < kCtrlHeadroom) {
        blocked = true;
        continue;
      }
      for (int msg = 0; msg < kOutboxMsgsPerPass; msg++) {
        const ShardGeom& g = p->geom[(size_t)shard];
        size_t per = (size_t)p->L * 4 + (size_t)g.wcnt * 4;
        {
          // window pre-check BEFORE paying for a quantize (node.py
          // _pump_outboxes): a full ledger leaves the mass in the
          // residual, where error feedback keeps it exact
          StLockGuard lk(p->mu);
          auto mit = p->members.find(hop);
          if (mit == p->members.end() || mit->second.dead ||
              mit->second.unacked.size() >= kSendWindow)
            break;
        }
        TxSlot* slot = p->txpool.acquire();
        uint8_t* body = slot->buf.data() + kBodyOff;
        int32_t nf = 0;
        uint32_t fseq = 0;
        {
          StLockGuard lk(p->mu);
          auto it = p->outbox.find(shard);
          if (it == p->outbox.end() || p->owned.count(shard)) {
            p->txpool.unref(slot);
            break;
          }
          // ONE measurement per message, the cascade-halving schedule,
          // every word plane in one AVX-512 memory pass — the classic
          // plane's machinery (slice_cascade_message; the per-frame
          // scalar path measured ~60 msgs/s where this shape does
          // thousands)
          nf = slice_cascade_message(p, g, it->second.data(), g.kcap,
                                     body, per, scr);
          if (nf == 0) {
            // drained to dust: FREE the outbox (the transient-memory
            // contract — state.drain_outbox_frames' twin)
            p->outbox.erase(it);
            p->txpool.unref(slot);
            break;
          }
          fseq = ++p->fwd_seq;
        }
        uint32_t off = (uint32_t)(kBodyOff - kFwdHdr);
        uint8_t* H = slot->buf.data() + off;
        H[0] = kFwd;
        uint32_t z = 0, wlo32 = (uint32_t)g.wlo, wc32 = (uint32_t)g.wcnt;
        std::memcpy(H + 1, &z, 4);  // per-link seq stamped by ledger_send
        std::memcpy(H + 5, &wlo32, 4);
        std::memcpy(H + 9, &wc32, 4);
        std::memcpy(H + 13, &p->origin, 4);
        std::memcpy(H + 17, &fseq, 4);
        slot->wire_off = off;
        slot->wire_len = (uint32_t)(kFwdHdr + (size_t)nf * per);
        if (!shard_dispatch(p, shard, slot, nullptr, H, slot->wire_len, -1,
                            sscratch, wscratch)) {
          // window filled / hop died mid-pump: park the encoded frames
          // under their identity (the residual was already debited —
          // error feedback lives in the frames now)
          StLockGuard lk(p->mu);
          shard_park(p, shard, H, slot->wire_len);
          p->txpool.unref(slot);
          break;
        }
        p->fwd_msgs_out++;
        p->fwd_frames_out += (uint64_t)nf;
        sent_any = true;
      }
    }
    shard_unpark(p, sscratch, wscratch);
    shard_retransmit(p);
    if (!sent_any && !p->stop.load()) {
      // blocked = mass waiting on sendq/window drain: come back on the
      // transport's timescale (a 20 ms nap here paced the whole plane
      // at ~250 msgs/s — the first bench run's wall); idle = wait for a
      // wake (add / ACK / route) with the retransmit-timer backstop
      StUniqueLock lk(p->wmu);
      auto nap_deadline = st_cv_deadline(blocked ? 0.0005 : 0.02);
      while (p->wseq <= seq_before && !p->stop.load()) {
        if (p->wcv.wait_until(lk.native(), nap_deadline) ==
            std::cv_status::timeout)
          break;
      }
    }
  }
}

// ---- shard receiver -------------------------------------------------------

void shard_recv_loop(ShardPlane* p) {
  std::vector<float> sscratch;
  std::vector<uint32_t> wscratch;
  while (!p->stop.load()) {
    uint64_t seq0 = st_node_data_seq(p->node);
    bool busy = false;
    std::vector<int32_t> ids;
    {
      StLockGuard lk(p->mu);
      for (auto& kv : p->members)
        if (!kv.second.dead) ids.push_back(kv.first);
    }
    for (int32_t id : ids) {
      for (int iter = 0; iter < 256; iter++) {
        const uint8_t* buf = nullptr;
        void* tok = nullptr;
        int32_t n = st_node_recv_take(p->node, id, &buf, &tok);
        if (n == 0) break;
        if (n < 0) {
          StLockGuard lk(p->mu);
          auto it = p->members.find(id);
          if (it != p->members.end()) it->second.dead = true;
          break;
        }
        busy = true;
        uint8_t kind = buf[0];
        if (kind == kFwd && (size_t)n >= kFwdHdr) {
          uint32_t seq;
          std::memcpy(&seq, buf + 1, 4);
          int32_t shard = -1;
          bool accept = false;
          {
            StLockGuard lk(p->mu);
            auto it = p->members.find(id);
            if (it != p->members.end()) {
              SMember& m = it->second;
              if (seq != (uint32_t)(m.rx_count + 1)) {
                // dup or gap: discard unapplied, RE-ANNOUNCE the ACK
                // (node.py: the dup usually means our ACK was lost)
                m.ack_due = true;
              } else {
                m.rx_count++;
                m.ack_due = true;
                uint32_t wlo;
                std::memcpy(&wlo, buf + 5, 4);
                shard = shard_of_word(p, wlo);
                accept = shard >= 0;
                if (shard < 0) p->fwd_undecodable++;
              }
            }
          }
          if (accept) {
            auto* tb = new TakenBuf();
            tb->plane = p;
            tb->tok = tok;
            tb->data = const_cast<uint8_t*>(buf);
            tb->len = (uint32_t)n;
            tb->from_link = id;
            tb->refs.store(1, std::memory_order_relaxed);
            p->taken_live.fetch_add(1, std::memory_order_acq_rel);
            if (!shard_dispatch(p, shard, nullptr, tb, tb->data, tb->len,
                                id, sscratch, wscratch)) {
              StLockGuard lk(p->mu);
              shard_park(p, shard, tb->data, tb->len);
              taken_unref(tb);
            }
          } else {
            st_node_take_free(p->node, id, tok);
          }
        } else if (kind == kAck && n == 9) {
          uint64_t count;
          std::memcpy(&count, buf + 1, 8);
          st_node_take_free(p->node, id, tok);
          bool opened = false;
          {
            StLockGuard lk(p->mu);
            auto it = p->members.find(id);
            if (it != p->members.end()) {
              SMember& m = it->second;
              bool progressed = false;
              while (!m.unacked.empty() && m.unacked.front().seq <= count) {
                shard_entry_unref(p, m.unacked.front());
                m.unacked.pop_front();
                progressed = true;
              }
              if (progressed) {
                m.ack_progress = EClock::now();
                m.retx_rounds = 0;
                opened = true;
              }
            }
          }
          if (opened) p->wake();  // window opened: outboxes/park may drain
        } else {
          // control plane (SHARD JSON, DIGEST, handshake strays): hand to
          // Python in arrival order
          {
            StLockGuard lk(p->cmu);
            p->ctrl.emplace_back(id, std::vector<uint8_t>(buf, buf + n));
          }
          st_node_take_free(p->node, id, tok);
        }
      }
      {
        StLockGuard lk(p->mu);
        auto it = p->members.find(id);
        if (it != p->members.end()) shard_flush_acks(p, id, it->second);
      }
    }
    if (!busy && !p->stop.load()) {
      st_node_wait_data(p->node, seq0, 0.05);
    }
  }
}

}  // namespace

// ---- C ABI ---------------------------------------------------------------

extern "C" {

__attribute__((visibility("default"))) void* st_engine_create(
    void* node, const int64_t* off, const int64_t* ns, const int64_t* padded,
    int64_t n_leaves, int64_t total, int64_t total_n,
    const float* init_values /* or NULL */, int32_t policy, int32_t per_leaf,
    int32_t burst, int32_t recv_cap, int32_t compat_frame_bytes,
    int32_t quarantine_send_failures, double ack_timeout_sec,
    int32_t ack_retry_limit, int32_t trace_wire) {
  if (compat_frame_bytes > 0 &&
      (n_leaves != 1 || compat_frame_bytes < 5 ||
       (int64_t)(compat_frame_bytes - 4) > total / 8))
    return nullptr;  // compat: one flat tensor, mask must fit the words
  auto* e = new Engine();
  e->node = node;
  e->obs_id = st_node_obs_id(node);  // tag engine events with the node
  e->L = n_leaves;
  e->total = total;
  e->total_n = total_n;
  e->W = total / 32;
  e->off.assign(off, off + n_leaves);
  e->ns.assign(ns, ns + n_leaves);
  e->padded.assign(padded, padded + n_leaves);
  e->policy = policy;
  e->per_leaf = per_leaf != 0;
  e->burst = burst < 1 ? 1 : (burst > 255 ? 255 : burst);
  // Compat bursts ARE protocol-legal: the reference stream is just
  // back-to-back fixed-size frames, so K quantized frames concatenated in
  // one wire message are indistinguishable from K sequential sends to any
  // reference peer — while costing ONE lock cycle + ONE write here.
  e->compat_bytes = compat_frame_bytes > 0 ? compat_frame_bytes : 0;
  e->recv_cap = recv_cap;
  e->quarantine = quarantine_send_failures > 0 ? quarantine_send_failures : 0;
  e->ack_timeout = ack_timeout_sec > 0 ? ack_timeout_sec : 0.0;
  // <= 0 coerces to 1 round, matching peer.py _check_retransmit's
  // max(1, ack_retry_limit) — the knob must mean the same thing on
  // both data planes
  e->ack_retry_limit = ack_retry_limit > 0 ? ack_retry_limit : 1;
  // trace context is native-framing only (the reference compat protocol
  // has no header to extend)
  e->trace_wire = (trace_wire != 0 && compat_frame_bytes <= 0) ? 1 : 0;
  {
    // values is ST_GUARDED_BY(mu); the engine is not shared yet, but
    // take the lock anyway — uncontended, and -Wthread-safety cannot
    // see "not published yet"
    StLockGuard lk(e->mu);
    e->values.assign((size_t)total, 0.0f);
    if (init_values)
      std::memcpy(e->values.data(), init_values, (size_t)total * 4);
  }
  // tx ring slot size: kBodyOff bytes of header room (body 8-aligned for
  // the codec kernels; headers pack flush against it) + the largest
  // message this engine can emit. The window (kSendWindow) bounds live
  // slots per link; keep_warm bounds idle memory.
  e->txpool.slot_bytes =
      kBodyOff + (size_t)e->burst * ((size_t)e->L * 4 + (size_t)e->W * 4);
  return e;
}

// r11 codec configuration — call between st_engine_create and
// st_engine_start (the sender thread reads these unlocked; the tx-slot
// ring is re-sized here for the widest message the mode can emit).
// prec_mode: 0 = fixed 1-bit, 1 = telemetry-adaptive precision (the
// governor upshifts capable links to sign2 when their residual RMS stops
// decaying and downshifts quiet ones), 2 = fixed sign2 on capable links
// (the A/B arm). cascade: frames quantized per memory pass (1 = the r10
// per-frame re-measured schedule; >1 = halving cascade, stcodec.c r11).
__attribute__((visibility("default"))) void st_engine_set_codec(
    void* h, int32_t prec_mode, double up_ratio, double down_ratio,
    double interval_sec, int32_t cascade) {
  if (!h) return;
  auto* e = (Engine*)h;
  e->prec_mode = prec_mode == 1 || prec_mode == 2 ? prec_mode : 0;
  if (up_ratio > 0) e->gov_up_ratio = up_ratio;
  if (down_ratio > 0) e->gov_down_ratio = down_ratio;
  if (interval_sec > 0) e->gov_interval = interval_sec;
  e->cascade = cascade < 1 ? 1 : (cascade > 64 ? 64 : cascade);
  if (e->prec_mode != 0 && !e->compat_bytes) {
    // slots must fit the widest message either precision can emit: the
    // sign2 burst is capped to the receive bound, which can exceed the
    // 1-bit burst's bytes when the 1-bit cap was frame-count-limited
    size_t per2 = frame_bytes(e) + (size_t)e->W * 4;
    int64_t cap2 = ((int64_t)e->recv_cap - (int64_t)kHdrV3) /
                   (int64_t)per2;
    if (cap2 < 1) cap2 = 1;
    if (cap2 > e->burst) cap2 = e->burst;
    size_t need = kBodyOff + (size_t)cap2 * per2;
    if (need > e->txpool.slot_bytes) e->txpool.slot_bytes = need;
  }
}

// r11: the peer on link_id advertised sign2 decode capability
// (compat.SYNC_FLAG_SIGN2 / the WELCOME flags byte) — emission to it may
// upshift. Without this call a link stays 1-bit forever (mixed-tree
// safety default).
__attribute__((visibility("default"))) int32_t st_engine_link_allow_sign2(
    void* h, int32_t link_id, int32_t allow) {
  if (!h) return 0;
  auto* e = (Engine*)h;
  StLockGuard lk(e->mu);
  auto it = e->links.find(link_id);
  if (it == e->links.end()) return 0;
  it->second.peer_sign2 = allow != 0;
  return 1;
}

// r14: the peer on link_id advertised the r14 capability (the SYNC/
// WELCOME shm flag — compat.SYNC_FLAG_SHM doubles as the r14 marker) —
// emission to it may use the aligned v3 framing, whose 24-byte header
// lets the receiver apply frames straight from the wire body. Without
// this call a link stays on v2 forever (mixed-tree safety default).
__attribute__((visibility("default"))) int32_t st_engine_link_wire_v3(
    void* h, int32_t link_id, int32_t allow) {
  if (!h) return 0;
  auto* e = (Engine*)h;
  StLockGuard lk(e->mu);
  auto it = e->links.find(link_id);
  if (it == e->links.end()) return 0;
  it->second.wire_v3 = allow != 0;
  return 1;
}

// r11: the governor's current precision choice for the link (1 or 2; 0 =
// unknown link / closed engine) — the st_link_precision gauge.
__attribute__((visibility("default"))) int32_t st_engine_link_precision(
    void* h, int32_t link_id) {
  if (!h) return 0;
  auto* e = (Engine*)h;
  StLockGuard lk(e->mu);
  auto it = e->links.find(link_id);
  if (it == e->links.end()) return 0;
  if (e->prec_mode == 2) return it->second.peer_sign2 ? 2 : 1;
  return it->second.prec;
}

__attribute__((visibility("default"))) void st_engine_start(void* h) {
  // Every entry point NULL-checks its handle: a late ctypes call after
  // st_engine_destroy must no-op/return-empty, never dereference NULL —
  // st_engine_counters(NULL) was a process-killing SIGSEGV under pytest's
  // failure repr (VERDICT r05 Weak #2). The Python facade guards too;
  // this is the defense-in-depth layer.
  if (!h) return;
  auto* e = (Engine*)h;
  e->send_thread = std::thread(sender_loop, e);
  e->recv_thread = std::thread(receiver_loop, e);
}

// Seal ingress for a graceful leave (see Engine::sealed).
__attribute__((visibility("default"))) void st_engine_seal(void* h) {
  if (!h) return;
  auto* e = (Engine*)h;
  e->sealed.store(true);
  st_obs_emit(e->obs_id, kEvSeal, -1, 0);
}

// Stop the engine threads. MUST be called before st_node_close (the threads
// block inside the node's condvars/queues).
__attribute__((visibility("default"))) void st_engine_stop(void* h) {
  if (!h) return;
  auto* e = (Engine*)h;
  e->stop.store(true);
  e->wake();
  if (e->send_thread.joinable()) e->send_thread.join();
  if (e->recv_thread.joinable()) e->recv_thread.join();
}

__attribute__((visibility("default"))) void st_engine_destroy(void* h) {
  auto* e = (Engine*)h;
  if (!e) return;
  // Drop the ledger references still held by attached links' unacked
  // entries (no rollback — the engine is dying, there is no residual left
  // to repair; Python detached/stashed everything it wanted first).
  {
    StLockGuard lk(e->mu);
    for (auto& kv : e->links) {
      for (auto& msg : kv.second.unacked)
        if (msg.slot) e->txpool.unref(msg.slot);
      kv.second.unacked.clear();
    }
  }
  // Transport release callbacks can still be in flight for a moment after
  // st_node_close returns: a link's queues are destroyed on its detached
  // I/O threads' exit path, AFTER the node's thread accounting is
  // decremented — so a queued zero-copy message's release(ctx) may fire
  // microseconds from now. Freeing the pool those callbacks point into
  // would be a use-after-free; wait for every slot reference to drain
  // (normally instantaneous), and prefer leaking to freeing under a live
  // thread if a wedged peer keeps one pinned.
  for (int i = 0;; i++) {
    bool busy = false;
    {
      StLockGuard lk(e->txpool.mu);
      for (auto& s : e->txpool.all_)
        if (s->refs.load(std::memory_order_acquire) != 0) {
          busy = true;
          break;
        }
    }
    if (!busy) break;
    if (i >= 2000) return;  // ~2 s: leak rather than free under a live thread
    std::this_thread::sleep_for(std::chrono::milliseconds(1));
  }
  delete e;
}

// values += sanitize(u), every residual += sanitize(u)
// (core.SharedTensor.add / reference addFromInternal src/sharedtensor.c:
// 334-344, with quirks Q7/Q9 fixed).
__attribute__((visibility("default"))) void st_engine_add(void* h,
                                                          const float* u) {
  if (!h) return;
  auto* e = (Engine*)h;
  {
    // r11 staged add: accumulate sanitize+clip(u) into the pending buffer
    // under add_mu ONLY — the trainer never waits on the data-plane
    // mutex (a multi-pass message quantize used to hold it ~ms). The
    // fold into values/residuals/carry — including the dead links whose
    // residual is the re-graft carry, and the fused partials refresh —
    // happens in fold_pending at the next data-plane safe point.
    StLockGuard alk(e->add_mu);
    if (e->upend.empty()) {
      // ufold (the fold scratch) is sized lazily by fold_pending — it is
      // mu-guarded and this path holds only add_mu
      e->upend.assign((size_t)e->total, 0.0f);
    }
    stc_accumulate_update_to(e->upend.data(), e->upend.data(), u,
                             e->off.data(), e->ns.data(), e->padded.data(),
                             e->L);
    if (e->trace_wire)
      e->pend_gen.store(st_obs_now_ns(), std::memory_order_relaxed);
    e->has_pending.store(true, std::memory_order_release);
  }
  e->updates++;
  e->wake();
}

__attribute__((visibility("default"))) void st_engine_read(void* h,
                                                           float* out) {
  if (!h) return;
  auto* e = (Engine*)h;
  StLockGuard lk(e->mu);
  fold_pending(e);
  std::memcpy(out, e->values.data(), (size_t)e->total * 4);
}

// Open a link with residual = values - peer_snapshot (the diff handshake
// seed, core.SharedTensor.new_link_diff). snapshot NULL => zero residual;
// seed!=0 => residual = full replica (reference join seeding). rx_init
// carries the cumulative receive count Python accumulated before attach so
// the ACK stream stays monotonic.
__attribute__((visibility("default"))) int32_t st_engine_attach(
    void* h, int32_t link_id, const float* snapshot, int32_t seed,
    uint64_t rx_init) {
  if (!h) return 0;
  auto* e = (Engine*)h;
  {
    StLockGuard lk(e->mu);
    fold_pending(e);  // the diff seed must include staged adds
    if (e->links.count(link_id)) return 0;  // already exists
    ELink& lk2 = e->links[link_id];
    lk2.resid.assign((size_t)e->total, 0.0f);
    if (snapshot) {
      for (int64_t i = 0; i < e->total; i++)
        lk2.resid[i] = e->values[i] - snapshot[i];
    } else if (seed) {
      std::memcpy(lk2.resid.data(), e->values.data(), (size_t)e->total * 4);
    }
    lk2.rx_count = rx_init;
    lk2.ack_sent = rx_init;
    lk2.dirty = true;
  }
  e->wake();
  return 1;
}

// r10: open a SUBSCRIBER link — read-only leaf, unledgered (no unacked
// entries, no ACK expectation, no go-back-N: a lost message is a seq gap
// the subscriber repairs with a resync handshake), optionally filtered to
// a word range (kRData framing ships only words [word_lo, word_lo+word_cnt)
// per frame; word_cnt <= 0 subscribes the whole table), with kFresh drain
// marks every fresh_interval_sec while idle. Residual seeds like
// st_engine_attach (values - snapshot; NULL snapshot = full replica), then
// zeroes outside the range — mass nobody will ever receive must not keep
// the sender busy. Attach and mode-set are ONE atomic step ON PURPOSE: a
// two-call attach-then-mark would let this sender emit a LEDGERED message
// in the window, whose missing ACK would black-hole the link.
// Returns 0 on duplicate link or compat mode (no SYNC handshake there, so
// no subscribers).
__attribute__((visibility("default"))) int32_t st_engine_attach_sub(
    void* h, int32_t link_id, const float* snapshot, uint64_t rx_init,
    int64_t word_lo, int64_t word_cnt, double fresh_interval_sec) {
  if (!h) return 0;
  auto* e = (Engine*)h;
  if (e->compat_bytes) return 0;
  {
    StLockGuard lk(e->mu);
    fold_pending(e);  // the sub seed must include staged adds
    if (e->links.count(link_id)) return 0;
    ELink& lk2 = e->links[link_id];
    lk2.resid.assign((size_t)e->total, 0.0f);
    if (snapshot) {
      for (int64_t i = 0; i < e->total; i++)
        lk2.resid[i] = e->values[i] - snapshot[i];
    } else {
      std::memcpy(lk2.resid.data(), e->values.data(), (size_t)e->total * 4);
    }
    if (word_cnt <= 0 || word_lo < 0 || word_lo + word_cnt > e->W) {
      word_lo = 0;
      word_cnt = e->W;
    }
    lk2.subscriber = true;
    lk2.wlo = word_lo;
    lk2.wcnt = word_cnt;
    lk2.ranged = (word_lo > 0 || word_cnt < e->W);
    if (lk2.ranged) {
      std::fill(lk2.resid.begin(), lk2.resid.begin() + word_lo * 32, 0.0f);
      std::fill(lk2.resid.begin() + (word_lo + word_cnt) * 32,
                lk2.resid.end(), 0.0f);
    }
    lk2.fresh_interval_ns =
        fresh_interval_sec > 0 ? (uint64_t)(fresh_interval_sec * 1e9) : 0;
    lk2.rx_count = rx_init;
    lk2.ack_sent = rx_init;
    lk2.dirty = true;
  }
  st_obs_emit(e->obs_id, kEvSubAttach, link_id, (uint64_t)word_cnt);
  e->wake();
  return 1;
}

// The wire-compat LEAF re-graft as ONE atomic step (the C analog of
// core.SharedTensor.regraft_reset_to_carry, same rationale): consume the
// carry, set the replica to EXACTLY the carry (fresh-joiner semantics — a
// true fresh joiner with pending adds holds them in values AND residual;
// the parent's full-replica re-seed then refills tree state additively),
// and open the new uplink with the carry as its residual. Resetting to
// zero instead would desync this node by the carry forever (split horizon
// never returns it). Returns 0 if the link already exists.
__attribute__((visibility("default"))) int32_t st_engine_compat_regraft(
    void* h, int32_t link_id) {
  if (!h) return 0;
  auto* e = (Engine*)h;
  {
    StLockGuard lk(e->mu);
    fold_pending(e);
    if (e->links.count(link_id)) return 0;
    ELink& l = e->links[link_id];
    if (e->has_carry) {
      l.resid = e->carry;             // copy: the residual the tree is owed
      e->values = std::move(e->carry);  // replica = exactly the carry
      e->has_carry = false;
      e->carry.clear();
      e->carry.shrink_to_fit();
    } else {
      std::fill(e->values.begin(), e->values.end(), 0.0f);
      l.resid.assign((size_t)e->total, 0.0f);
    }
    l.dirty = true;
  }
  e->wake();
  return 1;
}

// Park a dead uplink's residual (unacked rolled back) into the LIVE carry
// slot, which keeps accumulating add()/flood mass until the re-graft
// consumes it (see Engine::carry). Returns 1 if the link existed.
__attribute__((visibility("default"))) int32_t st_engine_stash_carry(
    void* h, int32_t link_id) {
  if (!h) return 0;
  auto* e = (Engine*)h;
  StLockGuard lk(e->mu);
  fold_pending(e);
  auto it = e->links.find(link_id);
  if (it == e->links.end()) return 0;
  rollback_unacked(e, it->second);
  if (!e->has_carry) {
    e->carry = std::move(it->second.resid);
    e->has_carry = true;
  } else {
    for (int64_t i = 0; i < e->total; i++)
      e->carry[i] += it->second.resid[i];
  }
  e->links.erase(it);
  return 1;
}

// Atomically read the replica snapshot AND consume the carry (one lock —
// an add() between the two reads would land in the snapshot but not the
// carry, re-creating the orphan-add loss this slot exists to fix).
// Either out pointer may be NULL to skip that copy: the BECAME_MASTER
// failover only needs the consume side effect (the carry's mass is already
// in the now-authoritative replica) and must not pay two full-table copies
// for it. Returns 1 when the carry existed (and, if carry_out is non-NULL,
// was written), 0 otherwise.
__attribute__((visibility("default"))) int32_t st_engine_take_carry_and_snapshot(
    void* h, float* carry_out, float* values_out) {
  if (!h) return 0;
  auto* e = (Engine*)h;
  StLockGuard lk(e->mu);
  fold_pending(e);
  if (values_out)
    std::memcpy(values_out, e->values.data(), (size_t)e->total * 4);
  if (!e->has_carry) return 0;
  if (carry_out)
    std::memcpy(carry_out, e->carry.data(), (size_t)e->total * 4);
  e->has_carry = false;
  e->carry.clear();
  e->carry.shrink_to_fit();
  return 1;
}

// Close a link; writes its undelivered residual (unacked frames rolled
// back) into out_resid. Returns 1 if the link existed.
__attribute__((visibility("default"))) int32_t st_engine_detach(
    void* h, int32_t link_id, float* out_resid) {
  if (!h) return 0;
  auto* e = (Engine*)h;
  StLockGuard lk(e->mu);
  fold_pending(e);
  auto it = e->links.find(link_id);
  if (it == e->links.end()) return 0;
  rollback_unacked(e, it->second);
  if (out_resid)
    std::memcpy(out_resid, it->second.resid.data(), (size_t)e->total * 4);
  e->links.erase(it);
  return 1;
}

// Apply k externally-decoded frames from src_link (which need not be
// attached — the pre-WELCOME flood-in case) to values + all other
// residuals. RX/ACK accounting for these stays with the caller.
__attribute__((visibility("default"))) void st_engine_inject(
    void* h, int32_t src_link, int32_t k, const float* scales,
    const uint32_t* words) {
  if (!h) return;
  auto* e = (Engine*)h;
  {
    StLockGuard lk(e->mu);
    // externally-decoded frames are python-tier 1-bit (the serve/handshake
    // paths never carry sign2)
    apply_batch(e, src_link, k, scales, words, 1);
  }
  e->wake();
}

__attribute__((visibility("default"))) int32_t st_engine_links(void* h,
                                                               int32_t* out,
                                                               int32_t cap) {
  if (!h) return 0;
  auto* e = (Engine*)h;
  StLockGuard lk(e->mu);
  int32_t n = 0;
  for (auto& kv : e->links) {
    if (n >= cap) break;
    out[n++] = kv.first;
  }
  return n;
}

__attribute__((visibility("default"))) double st_engine_residual_rms(
    void* h, int32_t link_id) {
  if (!h) return 0.0;
  auto* e = (Engine*)h;
  StLockGuard lk(e->mu);
  fold_pending(e);
  auto it = e->links.find(link_id);
  if (it == e->links.end()) {
    // the carry pseudo-slot (peer.CARRY_LINK == -1): an orphaned node's
    // owed mass lives here, not in any link — st_residual_norm must see
    // it or an orphan reads "quiesced" while still holding undelivered
    // updates. O(total) scan, but only reachable while a carry exists.
    if (link_id != -1 || !e->has_carry) return 0.0;
    double css = 0;
    const float* c = e->carry.data();
    for (int64_t i = 0; i < e->total; i++) css += (double)c[i] * (double)c[i];
    return std::sqrt(css / (double)e->total_n);
  }
  ELink& lk2 = it->second;
  // Fast path off the scale-partials cache: pss[] holds each leaf's
  // residual sum-of-squares, refreshed by every fused add/apply/quantize
  // pass — the exact quantity this scan would recompute. Matters because
  // the r09 digest beat (and drain()'s poll) samples this under e->mu
  // every interval on EVERY peer: a full O(total) walk here (64 MiB at
  // 16 Mi) would stall the data-plane threads that share the mutex. The
  // slow scan remains only for the rare cache-bypassing writes
  // (rollback, restore — pvalid false).
  double ss = 0;
  if (lk2.pvalid && (int64_t)lk2.pss.size() == e->L) {
    for (int64_t i = 0; i < e->L; i++) ss += lk2.pss[i];
  } else {
    const float* r = lk2.resid.data();
    for (int64_t i = 0; i < e->total; i++) ss += (double)r[i] * (double)r[i];
  }
  return std::sqrt(ss / (double)e->total_n);
}

__attribute__((visibility("default"))) int64_t st_engine_inflight(void* h) {
  if (!h) return 0;
  auto* e = (Engine*)h;
  StLockGuard lk(e->mu);
  int64_t n = 0;
  for (auto& kv : e->links) n += (int64_t)kv.second.unacked.size();
  return n;
}

// counters: [frames_out, frames_in, updates, msgs_out, msgs_in,
//            tx_slot_acquires, tx_slot_alloc_events, tx_slots_allocated,
//            retx_msgs, dedup_discards, rtt_ns_total, rtt_msgs,
//            hops_sum, hops_msgs, staleness_ns_last, traced_msgs_in,
//            sub_msgs_out, sub_fresh_out,
//            prec_upshifts, prec_downshifts, frames2_out, frames2_in]
// [5..7] are the r07 tx-ring pool stats (steady state: acquires grow,
// alloc_events flat); [8..11] are the r08 obs aggregates (go-back-N
// retransmitted messages, dup/gap discards, and the ACK round-trip
// sum-of-ns + sample count); [12..15] the r09 trace aggregates (hop-count
// sum + sample count over applied traced messages, the most recent
// apply-time staleness in ns, and the traced-message count); [16..17] the
// r10 serving aggregates (unledgered subscriber data messages sent +
// kFresh drain marks delivered; [18..21] the r11 adaptive-precision
// aggregates — obs/schema.py names all of them canonically).
__attribute__((visibility("default"))) void st_engine_counters(
    void* h, uint64_t* out22) {
  if (!h) {  // the SIGSEGV that aborted the whole suite (r05 Weak #2)
    for (int i = 0; i < 22; i++) out22[i] = 0;
    return;
  }
  auto* e = (Engine*)h;
  out22[0] = e->frames_out.load();
  out22[1] = e->frames_in.load();
  out22[2] = e->updates.load();
  out22[3] = e->msgs_out.load();
  out22[4] = e->msgs_in.load();
  out22[5] = e->txpool.acquires.load();
  out22[6] = e->txpool.alloc_events.load();
  {
    StLockGuard lk(e->txpool.mu);
    out22[7] = (uint64_t)e->txpool.all_.size();
  }
  out22[8] = e->retx_msgs.load();
  out22[9] = e->dedup_discards.load();
  out22[10] = e->rtt_ns_total.load();
  out22[11] = e->rtt_msgs.load();
  out22[12] = e->hops_sum.load();
  out22[13] = e->hops_msgs.load();
  out22[14] = e->staleness_ns_last.load();
  out22[15] = e->traced_msgs_in.load();
  out22[16] = e->sub_msgs_out.load();
  out22[17] = e->sub_fresh_out.load();
  out22[18] = e->prec_upshifts.load();
  out22[19] = e->prec_downshifts.load();
  out22[20] = e->frames2_out.load();
  out22[21] = e->frames2_in.load();
}

// r09 per-link convergence telemetry: out2[0] = origin-stamp age (ns) of
// the latest traced message applied from this link, out2[1] = its hop
// distance from the origin. Returns 1 when the link exists. The peer's
// registry collector renders these as the st_staleness_seconds{link=} and
// st_update_hops-adjacent gauges (obs/schema.py).
__attribute__((visibility("default"))) int32_t st_engine_link_obs(
    void* h, int32_t link_id, uint64_t* out2) {
  out2[0] = out2[1] = 0;
  if (!h) return 0;
  auto* e = (Engine*)h;
  StLockGuard lk(e->mu);
  auto it = e->links.find(link_id);
  if (it == e->links.end()) return 0;
  out2[0] = it->second.stale_ns;
  out2[1] = it->second.last_hops;
  return 1;
}

// Pop one control-plane message; returns its length (0 = none). link_out
// receives the source link id.
__attribute__((visibility("default"))) int32_t st_engine_poll_ctrl(
    void* h, int32_t* link_out, uint8_t* buf, int32_t cap) {
  if (!h) return 0;
  auto* e = (Engine*)h;
  StLockGuard lk(e->cmu);
  if (e->ctrl.empty()) return 0;
  auto& front = e->ctrl.front();
  *link_out = front.first;
  int32_t n = (int32_t)std::min<size_t>(front.second.size(), (size_t)cap);
  std::memcpy(buf, front.second.data(), (size_t)n);
  e->ctrl.pop_front();
  return n;
}

// r12 lifecycle quiesce: stop/resume NEW data production on the sender
// (the Engine::paused struct comment). ACKs, go-back-N retransmission,
// control traffic and drained-link FRESH beats keep running — the cluster
// consistent-cut barrier (comm/peer.py) drains every in-flight ledger to
// empty under this flag before any shard is captured.
__attribute__((visibility("default"))) void st_engine_pause(void* h,
                                                            int32_t p) {
  if (!h) return;
  auto* e = (Engine*)h;
  e->paused.store(p != 0);
  e->wake();
  if (p) {
    // SYNCHRONOUS pause: a sender pass that began before the store may
    // still be quantizing pre-pause residual state into the sendq. Wait
    // for two pass boundaries (the in-flight pass finishing + one full
    // pass that observed the flag), so by return NO data message produced
    // from pre-pause state can be enqueued after the caller's barrier
    // marker. Bounded (2 s) so a stopped/stuck sender can't wedge the
    // caller — the barrier's own quiesce gate still protects the capture.
    uint64_t g0 = e->sender_pass.load();
    auto deadline =
        std::chrono::steady_clock::now() + std::chrono::seconds(2);
    while (!e->stop.load() && e->sender_pass.load() < g0 + 2 &&
           std::chrono::steady_clock::now() < deadline) {
      e->wake();
      std::this_thread::sleep_for(std::chrono::milliseconds(1));
    }
  }
}

// Checkpoint restore: replace the replica and the residuals of links that
// exist both in the engine and in the checkpoint, atomically (the inverse
// of st_engine_snapshot_ex; utils/checkpoint.load_shared). ``aux``
// (nullable — 4 u64 per link, the snapshot_ex layout) restores each
// surviving link's precision-governor state: wire precision (byte 0 of
// aux[2]) and previous-RMS sample (aux[3], bit-cast double), with the
// vote counters reset — the governor resumes from the checkpointed
// verdict instead of a cold start. Live links' tx/rx wire seqs are NEVER
// touched: the TCP streams they count are live and their counters moved
// on — resetting them to checkpoint values would open a seq gap the
// go-back-N machinery reads as a retransmission storm / black hole. The
// quiesce barrier makes this sound: ledgers are drained empty before a
// cluster restore, so both ends of every link agree without seq surgery
// (the checkpointed seqs are persisted for the manifest's consistency
// audit, not for replay).
__attribute__((visibility("default"))) void st_engine_restore_ex(
    void* h, const float* values, int32_t n_links, const int32_t* ids,
    const float* resids, const uint64_t* aux /* nullable */) {
  if (!h) return;
  auto* e = (Engine*)h;
  {
    StLockGuard lk(e->mu);
    fold_pending(e);  // pre-restore adds belong to the superseded state
    std::memcpy(e->values.data(), values, (size_t)e->total * 4);
    for (int32_t i = 0; i < n_links; i++) {
      if (ids[i] == -1) {  // the carry pseudo-slot (snapshot_ex)
        e->carry.assign((size_t)e->total, 0.0f);
        std::memcpy(e->carry.data(), resids + (size_t)i * e->total,
                    (size_t)e->total * 4);
        e->has_carry = true;
        continue;
      }
      auto it = e->links.find(ids[i]);
      if (it == e->links.end()) continue;
      ELink& l = it->second;
      std::memcpy(l.resid.data(), resids + (size_t)i * e->total,
                  (size_t)e->total * 4);
      l.dirty = true;
      l.pvalid = false;  // restore bypasses the fused kernels
      if (aux) {
        int prec = (int)(aux[(size_t)i * 4 + 2] & 0xFF);
        if (prec == 1 || prec == 2) l.prec = prec;
        uint64_t gb = aux[(size_t)i * 4 + 3];
        double gp;
        std::memcpy(&gp, &gb, 8);
        if (std::isfinite(gp)) l.gov_prev = gp;  // -1.0 sentinel included
        l.gov_up = l.gov_down = 0;
        l.gov_quiet = 0;
        l.gov_bp = 0;
      }
    }
  }
  ((Engine*)h)->wake();
}

__attribute__((visibility("default"))) void st_engine_restore(
    void* h, const float* values, int32_t n_links, const int32_t* ids,
    const float* resids) {
  st_engine_restore_ex(h, values, n_links, ids, resids, nullptr);
}

// Consistent point-in-time (values, residuals, link aux) snapshot under
// ONE lock — the checkpoint primitive (core.SharedTensor.snapshot_all).
// resid_out must hold max_links * total floats; aux_out (nullable) holds
// 4 u64 per link: [0] tx wire seq (last DATA/BURST sent), [1] rx count
// (last in-order wire seq accepted == the cumulative ACK value), [2] the
// link's wire precision in byte 0 with flag bits at 8+ (bit 8 subscriber,
// bit 9 peer-sign2-capable, bit 10 ranged), [3] the governor's previous
// RMS sample bit-cast from double. One mutex acquisition makes the
// capture atomic against the codec threads: a cascade quantize runs
// entirely under e->mu, so sign2 residual planes and in-flight ledgered
// frames can never tear the snapshot (tests/test_checkpoint.py pins the
// byte-exact round trip). Returns the number of links written.
__attribute__((visibility("default"))) int32_t st_engine_snapshot_ex(
    void* h, float* values_out, int32_t* ids_out, float* resid_out,
    uint64_t* aux_out /* nullable */, int32_t max_links) {
  if (!h) return 0;
  auto* e = (Engine*)h;
  StLockGuard lk(e->mu);
  fold_pending(e);
  std::memcpy(values_out, e->values.data(), (size_t)e->total * 4);
  int32_t n = 0;
  for (auto& kv : e->links) {
    if (n >= max_links) break;
    ELink& l = kv.second;
    ids_out[n] = kv.first;
    std::memcpy(resid_out + (size_t)n * e->total, l.resid.data(),
                (size_t)e->total * 4);
    if (aux_out) {
      uint64_t* a = aux_out + (size_t)n * 4;
      a[0] = l.tx_seq;
      a[1] = l.rx_count;
      uint64_t flags = (l.subscriber ? 1u : 0u) | (l.peer_sign2 ? 2u : 0u) |
                       (l.ranged ? 4u : 0u);
      a[2] = (uint64_t)(l.prec & 0xFF) | (flags << 8);
      double gp = l.gov_prev;
      uint64_t gb;
      std::memcpy(&gb, &gp, 8);
      a[3] = gb;
    }
    n++;
  }
  if (e->has_carry && n < max_links) {
    // the carry is owed state: persist it as pseudo-link -1 (restore
    // recognizes the id)
    ids_out[n] = -1;
    std::memcpy(resid_out + (size_t)n * e->total, e->carry.data(),
                (size_t)e->total * 4);
    if (aux_out) std::memset(aux_out + (size_t)n * 4, 0, 32);
    n++;
  }
  return n;
}

__attribute__((visibility("default"))) int32_t st_engine_snapshot_all(
    void* h, float* values_out, int32_t* ids_out, float* resid_out,
    int32_t max_links) {
  return st_engine_snapshot_ex(h, values_out, ids_out, resid_out, nullptr,
                               max_links);
}

// ---- r17 engine-tier shard data plane ABI ---------------------------------

// Standalone slice-codec kernels (the parity surface): one quantize /
// apply step over a word range of the global layout, exactly
// state.SliceCodec's semantics. tests/test_shard_engine.py pins byte
// equality against the numpy twin on shared random state; the python
// tier itself stays numpy (the reference), so these exist for the plane
// and the tests, not as a codec fast path for state.py.
__attribute__((visibility("default"))) int32_t st_slice_quantize(
    const int64_t* off, const int64_t* ns, const int64_t* padded,
    int64_t n_leaves, int64_t word_lo, int64_t word_cnt, int32_t policy,
    float* resid, float* scales, uint32_t* words) {
  ShardPlane p;
  p.L = n_leaves;
  p.off.assign(off, off + n_leaves);
  p.ns.assign(ns, ns + n_leaves);
  p.padded.assign(padded, padded + n_leaves);
  p.policy = policy;
  p.recv_cap = 1 << 20;
  int64_t wl = word_lo, wc = word_cnt;
  shard_geom_init(&p, &wl, &wc, 1);
  return slice_quantize(&p, p.geom[0], resid, scales, words) ? 1 : 0;
}

__attribute__((visibility("default"))) int32_t st_slice_apply(
    const int64_t* off, const int64_t* ns, const int64_t* padded,
    int64_t n_leaves, int64_t word_lo, int64_t word_cnt, float* target,
    const float* scales, const uint32_t* words) {
  ShardPlane p;
  p.L = n_leaves;
  p.off.assign(off, off + n_leaves);
  p.ns.assign(ns, ns + n_leaves);
  p.padded.assign(padded, padded + n_leaves);
  p.recv_cap = 1 << 20;
  int64_t wl = word_lo, wc = word_cnt;
  shard_geom_init(&p, &wl, &wc, 1);
  return slice_apply(&p, p.geom[0], target, scales, words) ? 1 : 0;
}

// The pump's whole message build as a standalone kernel (the cascade
// parity surface): up to k frames written at wire strides into `frames`
// (frame f's global scale row at f*per, word plane at f*per + 4L; per =
// 4*n_leaves + 4*word_cnt). Returns the frame count; error feedback
// lands in `resid` in place. tests/test_shard_engine.py pins byte
// equality against state.py's measure + cascade_rows + quantize_at on
// shared random state.
__attribute__((visibility("default"))) int32_t st_slice_cascade(
    const int64_t* off, const int64_t* ns, const int64_t* padded,
    int64_t n_leaves, int64_t word_lo, int64_t word_cnt, int32_t policy,
    int32_t k, float* resid, uint8_t* frames) {
  ShardPlane p;
  p.L = n_leaves;
  p.off.assign(off, off + n_leaves);
  p.ns.assign(ns, ns + n_leaves);
  p.padded.assign(padded, padded + n_leaves);
  p.policy = policy;
  p.recv_cap = 1 << 20;
  int64_t wl = word_lo, wc = word_cnt;
  shard_geom_init(&p, &wl, &wc, 1);
  ShardScratch scr;
  size_t per = (size_t)n_leaves * 4 + (size_t)word_cnt * 4;
  return slice_cascade_message(&p, p.geom[0], resid, k, frames, per, scr);
}

// Create the plane. `wlo`/`wcnt` carry every shard's word range (the r16
// fixed-at-creation partition — python's ShardMap mirrors the same
// deterministic geometry). `recv_cap` is wire.frame_wire_bytes(spec):
// the per-message FWD burst cap derives from it exactly like
// wire.fwd_frames_cap. `origin` is the node's obs id — the end-to-end
// (origin, fwd_seq) identity's first half.
__attribute__((visibility("default"))) void* st_shard_create(
    void* node, const int64_t* off, const int64_t* ns, const int64_t* padded,
    int64_t n_leaves, int64_t total, int64_t total_n, int32_t n_shards,
    const int64_t* wlo, const int64_t* wcnt, int32_t policy,
    int32_t recv_cap, double ack_timeout_sec, int32_t ack_retry_limit,
    int32_t park_cap, uint32_t origin) {
  if (!node || n_shards <= 0) return nullptr;
  auto* p = new ShardPlane();
  p->node = node;
  p->obs_id = st_node_obs_id(node);
  p->origin = origin;
  p->L = n_leaves;
  p->total = total;
  p->total_n = total_n;
  p->W = total / 32;
  p->off.assign(off, off + n_leaves);
  p->ns.assign(ns, ns + n_leaves);
  p->padded.assign(padded, padded + n_leaves);
  p->policy = policy;
  p->recv_cap = recv_cap;
  p->ack_timeout = ack_timeout_sec > 0 ? ack_timeout_sec : 0.0;
  p->ack_retry_limit = ack_retry_limit > 0 ? ack_retry_limit : 1;
  p->park_cap = park_cap > 0 ? park_cap : 4096;
  shard_geom_init(p, wlo, wcnt, n_shards);
  size_t widest = 0;
  for (auto& g : p->geom) {
    size_t per = (size_t)p->L * 4 + (size_t)g.wcnt * 4;
    size_t need = (size_t)g.kcap * per;
    if (need > widest) widest = need;
  }
  p->txpool.slot_bytes = kBodyOff + widest;
  return p;
}

__attribute__((visibility("default"))) void st_shard_start(void* h) {
  if (!h) return;
  auto* p = (ShardPlane*)h;
  if (p->started) return;
  p->started = true;
  p->send_thread = std::thread(shard_sender_loop, p);
  p->recv_thread = std::thread(shard_recv_loop, p);
}

__attribute__((visibility("default"))) void st_shard_stop(void* h) {
  if (!h) return;
  auto* p = (ShardPlane*)h;
  p->stop.store(true);
  p->wake();
  if (p->send_thread.joinable()) p->send_thread.join();
  if (p->recv_thread.joinable()) p->recv_thread.join();
}

__attribute__((visibility("default"))) void st_shard_destroy(void* h) {
  auto* p = (ShardPlane*)h;
  if (!p) return;
  // drop ledger references (no rollback — FWD mass re-dispatches at
  // detach; a dying plane has nothing left to repair)
  {
    StLockGuard lk(p->mu);
    for (auto& kv : p->members) {
      for (auto& e : kv.second.unacked) shard_entry_unref(p, e);
      kv.second.unacked.clear();
    }
  }
  // wait for in-flight transport release callbacks (TxSlots AND taken rx
  // buffers) to drain — the st_engine_destroy rationale, verbatim
  for (int i = 0;; i++) {
    bool busy = p->taken_live.load(std::memory_order_acquire) != 0;
    if (!busy) {
      StLockGuard lk(p->txpool.mu);
      for (auto& s : p->txpool.all_)
        if (s->refs.load(std::memory_order_acquire) != 0) {
          busy = true;
          break;
        }
    }
    if (!busy) break;
    if (i >= 2000) return;  // ~2 s: leak rather than free under a live thread
    std::this_thread::sleep_for(std::chrono::milliseconds(1));
  }
  delete p;
}

// Attach a member link (handshake complete — python's WELCOME exchange).
// The plane's receiver owns the link's stream from here: FWD/ACK are
// consumed natively, everything else defers to st_shard_poll_ctrl.
__attribute__((visibility("default"))) int32_t st_shard_member_attach(
    void* h, int32_t link, uint64_t tx_init, uint64_t rx_init) {
  if (!h) return 0;
  auto* p = (ShardPlane*)h;
  StLockGuard lk(p->mu);
  if (p->members.count(link)) return 0;
  SMember m;
  m.tx_seq = tx_init;
  m.rx_count = rx_init;
  m.ack_sent = rx_init;
  m.ack_progress = EClock::now();
  p->members.emplace(link, std::move(m));
  return 1;
}

// Detach a member (LINK_DOWN): every unacked FWD re-dispatches under its
// UNCHANGED end-to-end identity — a copy that was actually delivered dies
// in the owner's dedup window instead of double-applying (node.py
// _on_link_down's discipline). Routeless frames park.
__attribute__((visibility("default"))) int32_t st_shard_member_detach(
    void* h, int32_t link) {
  if (!h) return 0;
  auto* p = (ShardPlane*)h;
  std::deque<ShardSent> entries;
  {
    StLockGuard lk(p->mu);
    auto it = p->members.find(link);
    if (it == p->members.end()) return 0;
    entries.swap(it->second.unacked);
    p->members.erase(it);
    if (p->uplink == link) p->uplink = -1;
    for (auto rit = p->route.begin(); rit != p->route.end();) {
      if (rit->second == link)
        rit = p->route.erase(rit);
      else
        ++rit;
    }
  }
  std::vector<float> ss;
  std::vector<uint32_t> ws;
  for (auto& e : entries) {
    const uint8_t* d = e.slot ? e.slot->buf.data() + e.slot->wire_off
                              : e.taken->data;
    uint32_t n = e.slot ? e.slot->wire_len : e.taken->len;
    uint32_t wlo;
    std::memcpy(&wlo, d + 5, 4);
    int32_t shard = shard_of_word(p, wlo);
    if (shard < 0) {
      shard_entry_unref(p, e);
      continue;
    }
    if (!shard_dispatch(p, shard, e.slot, e.taken,
                        const_cast<uint8_t*>(d), n, -1, ss, ws)) {
      StLockGuard lk(p->mu);
      shard_park(p, shard, d, n);
      shard_entry_unref(p, e);
    }
  }
  p->wake();
  return 1;
}

__attribute__((visibility("default"))) void st_shard_set_uplink(
    void* h, int32_t link) {
  if (!h) return;
  auto* p = (ShardPlane*)h;
  {
    StLockGuard lk(p->mu);
    p->uplink = link;
  }
  p->wake();
}

// Routes are Python's call (the own-announce flood stays control-plane);
// the plane mirrors them for the relay/pump hop choice. link < 0 clears.
__attribute__((visibility("default"))) void st_shard_set_route(
    void* h, int32_t shard, int32_t link) {
  if (!h) return;
  auto* p = (ShardPlane*)h;
  {
    StLockGuard lk(p->mu);
    if (link < 0)
      p->route.erase(shard);
    else
      p->route[shard] = link;
  }
  p->wake();  // parked frames may have a route now
}

// Mark a shard's outgoing handoff in flight (the _ho_sent discipline):
// while set, FWDs for it relay toward the successor instead of applying
// to the already-shipped slice (debited-mass conservation — the
// spec_shard apply_during_handoff mutation).
__attribute__((visibility("default"))) void st_shard_set_handoff(
    void* h, int32_t shard, int32_t on) {
  if (!h) return;
  auto* p = (ShardPlane*)h;
  StLockGuard lk(p->mu);
  if (on)
    p->ho_sent.insert(shard);
  else
    p->ho_sent.erase(shard);
}

// Adopt a shard slice (grant / handoff / restore). `values` NULL seeds
// zeros. Any outbox held toward the shard folds straight into the slice
// (we ARE the owner now — exact local apply), under the same mutex.
__attribute__((visibility("default"))) void st_shard_adopt(
    void* h, int32_t shard, const float* values) {
  if (!h) return;
  auto* p = (ShardPlane*)h;
  {
    StLockGuard lk(p->mu);
    if (shard < 0 || (size_t)shard >= p->geom.size()) return;
    const ShardGeom& g = p->geom[(size_t)shard];
    auto& vals = p->owned[shard];
    vals.assign((size_t)g.n_el, 0.0f);
    if (values) std::memcpy(vals.data(), values, (size_t)g.n_el * 4);
    auto ob = p->outbox.find(shard);
    if (ob != p->outbox.end()) {
      for (int64_t j = 0; j < g.n_el; j++) {
        float t = vals[(size_t)j] + ob->second[(size_t)j];
        if (t > kSat) t = kSat;
        if (t < -kSat) t = -kSat;
        vals[(size_t)j] = t;
      }
      p->outbox.erase(ob);
    }
    p->route.erase(shard);
    p->ho_sent.erase(shard);
  }
  p->wake();  // parked frames for this shard can apply now
}

// Release ownership (handoff tail / takeover re-grant). Returns 1 and
// copies the slice into `out` (when non-NULL) if it was owned.
__attribute__((visibility("default"))) int32_t st_shard_release(
    void* h, int32_t shard, float* out) {
  if (!h) return 0;
  auto* p = (ShardPlane*)h;
  StLockGuard lk(p->mu);
  auto it = p->owned.find(shard);
  if (it == p->owned.end()) return 0;
  if (out) std::memcpy(out, it->second.data(), it->second.size() * 4);
  p->owned.erase(it);
  p->ho_sent.erase(shard);
  return 1;
}

__attribute__((visibility("default"))) int32_t st_shard_owns(
    void* h, int32_t shard) {
  if (!h) return 0;
  auto* p = (ShardPlane*)h;
  StLockGuard lk(p->mu);
  return p->owned.count(shard) ? 1 : 0;
}

// Copy one owned slice out (serve-tier reads, handoff state chunks).
__attribute__((visibility("default"))) int32_t st_shard_read(
    void* h, int32_t shard, float* out) {
  if (!h) return 0;
  auto* p = (ShardPlane*)h;
  StLockGuard lk(p->mu);
  auto it = p->owned.find(shard);
  if (it == p->owned.end()) return 0;
  std::memcpy(out, it->second.data(), it->second.size() * 4);
  return 1;
}

// Merge an additive update (node.py add()'s hot half): the in-shard part
// applies EXACTLY to the owned slices, every out-of-shard part
// accumulates into its target shard's outbox residual — one mutex, like
// state.add_delta, so a racing adopt can never strand a deposit.
// `flat` is the full padded flat delta (spec.total floats).
__attribute__((visibility("default"))) void st_shard_add(
    void* h, const float* flat) {
  if (!h) return;
  auto* p = (ShardPlane*)h;
  {
    StLockGuard lk(p->mu);
    for (size_t s = 0; s < p->geom.size(); s++) {
      const ShardGeom& g = p->geom[s];
      const float* seg = flat + g.elo;
      bool nz = false;
      for (int64_t j = 0; j < g.n_el; j++)
        if (seg[j] != 0.0f) {
          nz = true;
          break;
        }
      if (!nz) continue;
      auto oit = p->owned.find((int32_t)s);
      if (oit != p->owned.end()) {
        float* vals = oit->second.data();
        for (int64_t j = 0; j < g.n_el; j++) {
          float t = vals[j] + seg[j] * g.live[(size_t)j];
          if (t > kSat) t = kSat;
          if (t < -kSat) t = -kSat;
          vals[j] = t;
        }
      } else {
        auto& ob = p->outbox[(int32_t)s];
        if (ob.empty()) ob.assign((size_t)g.n_el, 0.0f);
        float* r = ob.data();
        for (int64_t j = 0; j < g.n_el; j++)
          r[j] += seg[j] * g.live[(size_t)j];
      }
    }
  }
  p->updates++;
  p->wake();
}

// Re-seat a checkpointed outbox residual (restart path) — added to any
// mass already accumulated, like state.restore_outbox.
__attribute__((visibility("default"))) void st_shard_restore_outbox(
    void* h, int32_t shard, const float* resid) {
  if (!h) return;
  auto* p = (ShardPlane*)h;
  {
    StLockGuard lk(p->mu);
    if (shard < 0 || (size_t)shard >= p->geom.size()) return;
    const ShardGeom& g = p->geom[(size_t)shard];
    auto& ob = p->outbox[shard];
    if (ob.empty()) ob.assign((size_t)g.n_el, 0.0f);
    for (int64_t j = 0; j < g.n_el; j++) ob[(size_t)j] += resid[j];
  }
  p->wake();
}

// Merge (origin, seqs) into the end-to-end dedup window (handoff /
// restore) — sorted-merge + window trim, byte-compatible with node.py's
// _on_ho merge so mixed-tier handoffs interop.
__attribute__((visibility("default"))) int32_t st_shard_dedup_merge(
    void* h, uint32_t origin, const uint64_t* seqs, int64_t n) {
  if (!h) return 0;
  auto* p = (ShardPlane*)h;
  StLockGuard lk(p->mu);
  auto& win = p->dedup[origin];
  for (int64_t i = 0; i < n; i++) win.first.insert((uint32_t)seqs[i]);
  win.second.assign(win.first.begin(), win.first.end());  // sorted merge
  while (win.second.size() > kShardDedupWindow) {
    win.first.erase(win.second.front());
    win.second.pop_front();
  }
  return 1;
}

// Atomic checkpoint capture — owned slices, outbox residuals and dedup
// windows under ONE mutex acquisition (the r16 fourth-review invariant:
// a window seq must never persist without its applied mass). Returns the
// owned-slice count; ids/values land in ascending shard order, values
// concatenated (the caller knows each shard's n_el from the map
// geometry). `dd_n`/`n_ob` receive the dedup pair count and outbox count.
__attribute__((visibility("default"))) int32_t st_shard_snapshot(
    void* h, int32_t* owned_ids, float* owned_vals, int32_t* outbox_ids,
    float* outbox_vals, uint32_t* dd_origins, uint64_t* dd_seqs,
    int64_t dd_cap, int64_t* dd_n, int32_t* n_ob) {
  *dd_n = 0;
  *n_ob = 0;
  if (!h) return 0;
  auto* p = (ShardPlane*)h;
  StLockGuard lk(p->mu);
  int32_t no = 0;
  size_t voff = 0;
  for (auto& kv : p->owned) {
    owned_ids[no++] = kv.first;
    std::memcpy(owned_vals + voff, kv.second.data(), kv.second.size() * 4);
    voff += kv.second.size();
  }
  int32_t nb = 0;
  voff = 0;
  for (auto& kv : p->outbox) {
    outbox_ids[nb++] = kv.first;
    std::memcpy(outbox_vals + voff, kv.second.data(), kv.second.size() * 4);
    voff += kv.second.size();
  }
  *n_ob = nb;
  int64_t dn = 0;
  for (auto& kv : p->dedup)
    for (uint32_t s : kv.second.second) {
      if (dn >= dd_cap) break;
      dd_origins[dn] = kv.first;
      dd_seqs[dn] = s;
      dn++;
    }
  *dd_n = dn;
  return no;
}

// Total (origin, fwd_seq) pairs across every dedup window — sizes the
// export/snapshot buffers so a many-origin cluster's windows never
// silently truncate (each origin holds at most kShardDedupWindow).
__attribute__((visibility("default"))) int64_t st_shard_dedup_size(void* h) {
  if (!h) return 0;
  auto* p = (ShardPlane*)h;
  StLockGuard lk(p->mu);
  int64_t n = 0;
  for (auto& kv : p->dedup) n += (int64_t)kv.second.second.size();
  return n;
}

// Export the dedup windows alone (the handoff ride-along: per-origin
// state, no reason to copy every owned slice the way st_shard_snapshot
// must). Returns the pair count written (<= cap).
__attribute__((visibility("default"))) int64_t st_shard_dedup_export(
    void* h, uint32_t* origins, uint64_t* seqs, int64_t cap) {
  if (!h) return 0;
  auto* p = (ShardPlane*)h;
  StLockGuard lk(p->mu);
  int64_t dn = 0;
  for (auto& kv : p->dedup)
    for (uint32_t s : kv.second.second) {
      if (dn >= cap) return dn;
      origins[dn] = kv.first;
      seqs[dn] = s;
      dn++;
    }
  return dn;
}

__attribute__((visibility("default"))) uint32_t st_shard_fwd_seq(void* h) {
  if (!h) return 0;
  auto* p = (ShardPlane*)h;
  StLockGuard lk(p->mu);
  return p->fwd_seq;
}

__attribute__((visibility("default"))) void st_shard_set_fwd_seq(
    void* h, uint32_t seq) {
  if (!h) return;
  auto* p = (ShardPlane*)h;
  StLockGuard lk(p->mu);
  p->fwd_seq = seq;
}

// Resident f32 state bytes (owned slices + live outboxes): the chaos
// harness's per-node bound (subscriber residuals stay python-side and
// are added there).
__attribute__((visibility("default"))) int64_t st_shard_alloc_bytes(
    void* h) {
  if (!h) return 0;
  auto* p = (ShardPlane*)h;
  StLockGuard lk(p->mu);
  int64_t total = 0;
  for (auto& kv : p->owned) total += (int64_t)kv.second.size() * 4;
  for (auto& kv : p->outbox) total += (int64_t)kv.second.size() * 4;
  return total;
}

__attribute__((visibility("default"))) int64_t st_shard_outbox_bytes(
    void* h) {
  if (!h) return 0;
  auto* p = (ShardPlane*)h;
  StLockGuard lk(p->mu);
  int64_t total = 0;
  for (auto& kv : p->outbox) total += (int64_t)kv.second.size() * 4;
  return total;
}

__attribute__((visibility("default"))) int64_t st_shard_owned_words(
    void* h) {
  if (!h) return 0;
  auto* p = (ShardPlane*)h;
  StLockGuard lk(p->mu);
  int64_t total = 0;
  for (auto& kv : p->owned)
    total += p->geom[(size_t)kv.first].wcnt;
  return total;
}

// True when every outbox residual is within tol of idle AND every ledger
// is empty AND nothing is parked — node.py drained()'s engine half.
__attribute__((visibility("default"))) int32_t st_shard_idle(void* h,
                                                             double tol) {
  if (!h) return 1;
  auto* p = (ShardPlane*)h;
  StLockGuard lk(p->mu);
  if (!p->parked.empty()) return 0;
  for (auto& kv : p->members)
    if (!kv.second.unacked.empty()) return 0;
  for (auto& kv : p->outbox)
    for (float v : kv.second)
      if (std::fabs(v) > tol) return 0;
  return 1;
}

// Counter snapshot:
// [0 fwd_msgs_out, 1 fwd_msgs_in, 2 relayed, 3 dedup_discards,
//  4 park_drops, 5 parked (gauge), 6 retx_msgs, 7 updates,
//  8 fwd_frames_out, 9 fwd_frames_in, 10 tx_slot_acquires,
//  11 tx_slot_alloc_events, 12 fwd_undecodable, 13 inflight (gauge)]
__attribute__((visibility("default"))) void st_shard_counters(
    void* h, uint64_t* out14) {
  for (int i = 0; i < 14; i++) out14[i] = 0;
  if (!h) return;
  auto* p = (ShardPlane*)h;
  out14[0] = p->fwd_msgs_out.load();
  out14[1] = p->fwd_msgs_in.load();
  out14[2] = p->relayed.load();
  out14[3] = p->dedup_discards.load();
  out14[4] = p->park_drops.load();
  out14[6] = p->retx_msgs.load();
  out14[7] = p->updates.load();
  out14[8] = p->fwd_frames_out.load();
  out14[9] = p->fwd_frames_in.load();
  out14[10] = p->txpool.acquires.load();
  out14[11] = p->txpool.alloc_events.load();
  out14[12] = p->fwd_undecodable.load();
  uint64_t parked_n = 0, inflight = 0;
  {
    StLockGuard lk(p->mu);
    parked_n = (uint64_t)p->parked.size();
    for (auto& kv : p->members) inflight += (uint64_t)kv.second.unacked.size();
  }
  out14[5] = parked_n;
  out14[13] = inflight;
}

// Pop one control-plane message the receiver deferred to Python (same
// contract as st_engine_poll_ctrl).
__attribute__((visibility("default"))) int32_t st_shard_poll_ctrl(
    void* h, int32_t* link_out, uint8_t* buf, int32_t cap) {
  if (!h) return 0;
  auto* p = (ShardPlane*)h;
  StLockGuard lk(p->cmu);
  if (p->ctrl.empty()) return 0;
  auto& front = p->ctrl.front();
  *link_out = front.first;
  int32_t n = (int32_t)std::min<size_t>(front.second.size(), (size_t)cap);
  std::memcpy(buf, front.second.data(), (size_t)n);
  p->ctrl.pop_front();
  return n;
}

}  // extern "C"
