// st_cv.h: condition-variable deadline waits pinned to the SYSTEM clock.
//
// Why this exists (r13 TSan arm): with glibc >= 2.30, libstdc++ implements
// steady-clock condvar waits — condition_variable::wait_for and
// wait_until(steady_clock::time_point) — via pthread_cond_clockwait, which
// this image's libtsan (gcc 10) does NOT intercept. The wait's internal
// unlock/relock is then invisible to ThreadSanitizer: its lock state
// corrupts and every later operation on that mutex yields bogus
// "double lock of a mutex" / data-race reports (reproduced in isolation;
// this is why the pre-r13 native/tsan build was abandoned as unusable).
// System-clock deadlines go through the intercepted pthread_cond_timedwait
// on every toolchain.
//
// Cost of the pin: a wall-clock step (NTP) during a wait stretches or
// shortens THAT wait by at most its own bound. Every wait in the native
// tier is a bounded tick inside a re-check loop (2 ms .. 1 s), so a step
// costs one tick of latency, never a missed wakeup — the same contract
// the codec pool's CLOCK_REALTIME pthread_cond_timedwait has always had.
//
// Use st_cv_deadline(sec) once per logical wait and loop on
// cv.wait_until(lk, deadline): the total timeout spans spurious wakeups,
// exactly like the wait_for(pred) form it replaces.

#ifndef ST_CV_H_
#define ST_CV_H_

#include <chrono>

using StCvClock = std::chrono::system_clock;

inline StCvClock::time_point st_cv_deadline(double sec) {
  return StCvClock::now() + std::chrono::duration_cast<StCvClock::duration>(
                                std::chrono::duration<double>(sec));
}

#endif  // ST_CV_H_
