/* stcodec: native host-tier codec hot loops.
 *
 * The reference's entire codec is ~30 lines of C inside its link threads
 * (reference src/sharedtensor.c:106-111 receiver, :153-174 sender), measured
 * at 202 M elem/s on one core (BASELINE.md) — the system's bottleneck. Our
 * host tier's numpy implementation (ops/codec_np.py) costs ~8 memory passes
 * per frame where the C loop needs ~2 fused ones; this library provides
 * those fused loops for CPU peers. The TPU tier is ops/codec_pallas.py; the
 * numpy tier remains the always-available fallback and the semantic
 * reference for these functions (bit-identical given the same scales).
 *
 * Table layout (ops/table.py): one flat f32 buffer; leaf i occupies
 * [off[i], off[i]+padded[i]) with ns[i] live elements at the front, padding
 * exactly 0. Bits are LSB-first: flat bit j -> word[j/32] bit j%32
 * (ops/packing.py wire contract; byte-identical to the reference's
 * data[i/8] |= 1 << (i%8)).
 *
 * Plain C ABI for ctypes (no pybind11 in this image).
 *
 * Threading: each entry point runs serial below ST_CODEC_PAR_MIN elements
 * (one link engine per thread, like the reference — small tables are
 * latency-bound and a pool handoff would only add wakeup cost). Above the
 * threshold the loops run chunked on a small process-wide worker pool
 * (stc_pool below): chunks are fixed 2 Mi-element word-aligned ranges, so
 * reduction grouping — and therefore every scale partial — is a pure
 * function of the table layout, NOT of the thread count; results are
 * deterministic for any ST_CODEC_THREADS value, differing from the serial
 * pass only by the ~1-ulp summation-order tolerance every scale consumer
 * already accepts (scales ride the wire, receivers never recompute them).
 * Elementwise loops (quantize/apply/add) are bit-exact under any split.
 */

#include <stdint.h>
#include <string.h>

#include "st_annotations.h" /* clang -Wthread-safety vocabulary (no-op on gcc) */

#define EXPORT __attribute__((visibility("default")))

/* AVX-512 fast paths with RUNTIME dispatch. The reference's scalar loops run
 * ~200 M elem/s/core (BASELINE.md); the sign-quantize and apply loops below
 * are 1-bit-per-float mask ops, which AVX-512 expresses directly
 * (compare->__mmask16 is the codec's bitmask, bit-for-bit). Scalar code
 * stays as the portable fallback and the semantic reference.
 *
 * Why runtime and not -march=native: a prebuilt libstcodec.so can travel to
 * another machine (docker image, rsync'd checkout, NFS) where make's
 * mtime-only check sees it as fresh — compile-time-only AVX-512 would then
 * SIGILL the peer process on a non-AVX-512 host. The AVX-512 bodies are
 * compiled via __attribute__((target(...))) and selected per-process with
 * __builtin_cpu_supports, so the same .so is correct everywhere. */
#if defined(__x86_64__) && defined(__GNUC__) && !defined(ST_ANALYZE_NO_SIMD)
#include <immintrin.h>
#define ST_AVX512 1
static int st_has_avx512(void) {
  /* relaxed atomics (TSan arm finding): two engine threads can run the
   * first large-table kernels concurrently, and the lazy init of a plain
   * int was a write/read race. Both writers store the same value, so
   * relaxed ordering is sufficient — the guard is the access atomicity. */
  static int cached = -1;
  int c = __atomic_load_n(&cached, __ATOMIC_RELAXED);
  if (c < 0) {
    c = __builtin_cpu_supports("avx512f") &&
        __builtin_cpu_supports("avx512dq");
    __atomic_store_n(&cached, c, __ATOMIC_RELAXED);
  }
  return c;
}
#define ST_TARGET_AVX512 __attribute__((target("avx512f,avx512dq")))
/* The scalar loops are the only path on non-AVX-512 x86; without
 * -march=native they'd compile to baseline SSE2. target_clones gives them
 * an AVX2 auto-vectorized clone behind the same runtime-dispatch safety. */
#define ST_CLONES __attribute__((target_clones("avx2", "default")))
#else
#define ST_CLONES
#endif

/* ---- worker pool ---------------------------------------------------------
 *
 * One process-wide pool, lazily spawned on the first large-table call.
 * Thread count: ST_CODEC_THREADS env (<=1 disables), else min(nproc, 8).
 * Submitters serialize on job_mu with TRYLOCK: if the pool is busy (the
 * engine's sender and receiver threads can both hit large-table codec ops
 * concurrently) the second caller just runs its loop inline — never blocks,
 * never deadlocks. Workers pull chunk indices from one atomic counter.
 * Fork safety: Python peers fork worker processes (multiprocessing); pool
 * threads do not survive fork, so an atfork child handler marks the pool
 * dead and every later call in the child runs inline (correct, just
 * serial) until nothing — the child can never wait on absent workers. */
#if defined(__unix__)
#define ST_POOL 1
#include <pthread.h>
#include <stdatomic.h>
#include <stdlib.h>
#include <unistd.h>

/* chunk granularity: 128 Ki elements = 512 KiB of f32 (multiple of 32, so
 * a chunk boundary never splits a packed word); parallel threshold below.
 * r07: was 2 Mi / 4 Mi — that left every table below 4 Mi elements (the
 * 1 Mi headline bench among them) single-threaded; 512 KiB chunks keep
 * the per-chunk work far above the pool handoff cost (~µs vs ~50 µs of
 * memory traffic) while letting mid-size tables use the pool. The
 * decomposition stays a pure function of the layout (NOT of the thread
 * count), so partials grouping remains deterministic for any
 * ST_CODEC_THREADS — only the grouping constant changed, moving scale
 * partials within the same ~1-ulp summation-order tolerance the tier
 * contract already accepts. */
#define ST_CHUNK_ELEMS ((int64_t)128 * 1024)
#define ST_PAR_MIN_ELEMS ((int64_t)256 * 1024)

/* Bounded spin (in pause-loop iterations) a worker burns watching for the
 * next job before it blocks on the condvar, and the submitter burns
 * watching for completion before it blocks on cv_done. The steady-state
 * burst loop submits one quantize job per frame back-to-back (~0.1-0.3 ms
 * apart at 1 Mi); a condvar sleep/wake on every one of those costs tens of
 * µs per worker per job — comparable to the per-chunk work itself at 512 KiB
 * chunks, which is exactly why the old 2 Mi chunking saw no speedup below
 * 4 Mi elements. The spin window catches the back-to-back case; an idle
 * process pays it once per quiesce, then sleeps as before. */
#define ST_SPIN_ITERS 20000

#if defined(__x86_64__)
#define stc_cpu_relax() __builtin_ia32_pause()
#else
#define stc_cpu_relax() ((void)0)
#endif

typedef void (*stc_seg_fn)(void *ctx, int64_t seg);

/* pthread_mutex_t wrapped as a clang thread-safety "capability" so pool
 * fields can carry ST_GUARDED_BY and the analysis checks the lock
 * discipline (st_annotations.h; plain pthread types are not capabilities).
 * Lock order: job_mu -> mu (the submitter wakes sleepers / sleeps on
 * cv_done while holding job_mu); workers take mu alone. */
typedef struct ST_CAPABILITY("mutex") stc_mutex {
  pthread_mutex_t m;
} stc_mutex_t;

/* The wrapper BODIES are the trusted primitive — pthread_mutex_* is not
 * annotated, so without the no-analysis escape the analysis flags the
 * acquire/release contract as unfulfilled inside each wrapper. Callers
 * still get the full contract from the attributes. */
static inline void stc_mutex_lock(stc_mutex_t *mu)
    ST_ACQUIRE(*mu) ST_NO_THREAD_SAFETY_ANALYSIS {
  pthread_mutex_lock(&mu->m);
}
static inline void stc_mutex_unlock(stc_mutex_t *mu)
    ST_RELEASE(*mu) ST_NO_THREAD_SAFETY_ANALYSIS {
  pthread_mutex_unlock(&mu->m);
}
/* returns 0 on success, like pthread_mutex_trylock */
static inline int stc_mutex_trylock(stc_mutex_t *mu)
    ST_TRY_ACQUIRE(0, *mu) ST_NO_THREAD_SAFETY_ANALYSIS {
  return pthread_mutex_trylock(&mu->m);
}

static struct {
  stc_mutex_t mu;
  pthread_cond_t cv_job, cv_done;
  stc_mutex_t job_mu; /* serializes submitters (trylock) */
  /* 0 = not yet, 1 = live, -1 = dead (fork child / threading disabled).
   * ATOMIC: stc_pool_up's fast path reads it lock-free on every
   * large-table call (a plain int there was a data race against the
   * slow path's locked write — exactly the bug class this PR's TSan arm
   * exists to catch; the transition is monotonic 0 -> {1,-1} so the
   * value a racy reader observes is still always valid). */
  _Atomic int started;
  int nworkers ST_GUARDED_BY(mu);
  uint64_t gen ST_GUARDED_BY(job_mu);
  /* job fields are relaxed atomics published under the agen seqlock (see
   * below): plain fields raced the next submitter's writes once the
   * publish mutex was dropped — a worker preempted between adopting agen
   * and reading nseg could pair job N's counter tag with job N+1's nseg
   * and claim a chunk both jobs then process. */
  _Atomic(stc_seg_fn) fn;
  void *_Atomic ctx;
  _Atomic int64_t nseg;
  /* generation-tagged work counter: (gen & 0xffffffff) << 32 | next_index.
   * The tag closes a straggler race: a worker that woke for job G and
   * snapshotted fn/ctx/nseg can be preempted BEFORE its first pop while
   * the other threads finish all of G; the submitter then returns, frees
   * G's chunks (a stack ctx), and publishes job G+1 — an untagged counter
   * would hand the stale worker G+1's chunk indices to run with G's dead
   * fn/ctx (use-after-free) while G+1 silently loses those chunks. With
   * the tag, a pop whose generation no longer matches fails and the
   * straggler falls through to re-wait (ADVICE r05 finding 2). */
  _Atomic uint64_t next;
  /* r11 lock-free hot path: the per-job mutex round trips (publish
   * broadcast + every worker's start/finish acquisition) measured as
   * ~100 us of a ~250 us pass once the cascade cut the pass COUNT 8-fold
   * — the handoff became the wall. Steady state now touches no mutex at
   * all: agen is a SEQLOCK word, (gen << 1) | writing — the submitter
   * flips it odd (acq_rel RMW, so the field stores cannot hoist above
   * it), stores fn/ctx/nseg/afin/next, then release-stores the new even
   * tag; a worker snapshots the fields between two agen loads and
   * retries on odd or mismatch, so a snapshot is always ONE job's
   * consistent set and its pops tag-check against that same gen.
   * Workers count completions into afin, a single generation-tagged
   * (gen32 << 32 | finished) word the submitter spins on. The
   * mutex/condvar pair remains ONLY as the idle-sleep fallback: workers
   * register in `sleepers` and timed-wait (bounded, so the publisher's
   * racy sleepers check can never lose a wakeup for more than one
   * tick), and a submitter whose spin expires sets sub_waiting and
   * timed-waits on cv_done. */
  _Atomic uint64_t agen;
  _Atomic uint64_t afin; /* (gen32 << 32) | chunks finished for that gen */
  /* modified under mu (the condvar handshake needs that); ATOMIC because
   * the publisher reads it without mu — the missed-wakeup that read can
   * suffer is bounded by the 2 ms timedwait tick, but the access itself
   * must not be a plain-int data race */
  _Atomic int sleepers;
  _Atomic int sub_waiting;
} g_pool = {.mu = {PTHREAD_MUTEX_INITIALIZER},
            .cv_job = PTHREAD_COND_INITIALIZER,
            .cv_done = PTHREAD_COND_INITIALIZER,
            .job_mu = {PTHREAD_MUTEX_INITIALIZER}};

/* Pop one chunk index for generation `gen`, or -1 when the job is exhausted
 * OR the counter now belongs to a different generation (stale worker). */
static int64_t stc_pool_pop(uint64_t gen, int64_t nseg) {
  uint64_t cur = atomic_load(&g_pool.next);
  for (;;) {
    if ((uint32_t)(cur >> 32) != (uint32_t)gen) return -1; /* stale gen */
    int64_t s = (int64_t)(cur & 0xffffffffu);
    if (s >= nseg) return -1; /* job exhausted */
    /* on failure `cur` is refreshed; re-check gen before retrying */
    if (atomic_compare_exchange_weak(&g_pool.next, &cur, cur + 1)) return s;
  }
}

static void *stc_pool_worker(void *arg) {
  (void)arg;
  uint64_t seen = 0;
  for (;;) {
    /* spin phase: the steady-state sender submits jobs back-to-back, and
     * a condvar sleep/wake per job costs more than a whole 512 KiB chunk —
     * watch the lock-free generation mirror before sleeping. */
    int spun = 0;
    while (atomic_load_explicit(&g_pool.agen, memory_order_acquire) ==
           seen) {
      if (++spun >= ST_SPIN_ITERS) {
        /* idle: sleep (the only mutex on this thread's lifetime path).
         * timedwait bounds the publisher's racy sleepers check — a
         * publish that misses a just-registering sleeper costs one tick,
         * never a lost wakeup. */
        stc_mutex_lock(&g_pool.mu);
        g_pool.sleepers++;
        while (atomic_load_explicit(&g_pool.agen, memory_order_acquire) ==
               seen) {
          struct timespec ts;
          clock_gettime(CLOCK_REALTIME, &ts);
          ts.tv_nsec += 2000000; /* 2 ms tick */
          if (ts.tv_nsec >= 1000000000) {
            ts.tv_sec++;
            ts.tv_nsec -= 1000000000;
          }
          pthread_cond_timedwait(&g_pool.cv_job, &g_pool.mu.m, &ts);
        }
        g_pool.sleepers--;
        stc_mutex_unlock(&g_pool.mu);
        break;
      }
      stc_cpu_relax();
    }
    /* seqlock read: snapshot the job fields between two agen loads and
     * adopt only a stable, even (not mid-publish) tag — the snapshot is
     * then ONE job's consistent {fn, ctx, nseg}, and pops tag-check
     * against that same generation. A newer job replacing the counter
     * makes our pops fail and we loop to re-adopt (the straggler
     * discipline, ADVICE r05 finding 2 — unchanged, just lock-free). */
    uint64_t g1 = atomic_load_explicit(&g_pool.agen, memory_order_acquire);
    if ((g1 & 1) != 0 || g1 == seen) continue;
    stc_seg_fn fn = atomic_load_explicit(&g_pool.fn, memory_order_relaxed);
    void *ctx = atomic_load_explicit(&g_pool.ctx, memory_order_relaxed);
    int64_t nseg = atomic_load_explicit(&g_pool.nseg, memory_order_relaxed);
    atomic_thread_fence(memory_order_acquire);
    if (atomic_load_explicit(&g_pool.agen, memory_order_relaxed) != g1)
      continue; /* a publish raced the snapshot: re-adopt */
    seen = g1;
    uint64_t gen = g1 >> 1;
    int64_t done = 0;
    for (;;) {
      int64_t s = stc_pool_pop(gen, nseg);
      if (s < 0) break;
      fn(ctx, s);
      done++;
    }
    if (done) {
      /* generation-tagged completion: only count into OUR job's word (a
       * straggler of a finished job sees a mismatched tag and drops its
       * count — that job already completed without it). */
      uint64_t cur = atomic_load(&g_pool.afin);
      for (;;) {
        if ((uint32_t)(cur >> 32) != (uint32_t)gen) break;
        if (atomic_compare_exchange_weak(&g_pool.afin, &cur,
                                         cur + (uint64_t)done)) {
          if ((int64_t)((cur & 0xffffffffu) + (uint64_t)done) >= nseg &&
              atomic_load_explicit(&g_pool.sub_waiting,
                                   memory_order_acquire)) {
            stc_mutex_lock(&g_pool.mu);
            pthread_cond_broadcast(&g_pool.cv_done);
            stc_mutex_unlock(&g_pool.mu);
          }
          break;
        }
      }
    }
  }
  return NULL;
}

static void stc_pool_child(void) {
  /* fork child: single-threaded by definition, but keep the store atomic
   * so the field has exactly one access discipline everywhere */
  atomic_store_explicit(&g_pool.started, -1, memory_order_relaxed);
}

static int stc_pool_threads(void) {
  static int cached = 0;
  if (!cached) {
    const char *env = getenv("ST_CODEC_THREADS");
    long v = env ? strtol(env, NULL, 10) : 0;
    if (v <= 0) {
      long np = sysconf(_SC_NPROCESSORS_ONLN);
      v = np < 1 ? 1 : (np > 8 ? 8 : np);
    }
    cached = v > 64 ? 64 : (int)v;
  }
  return cached;
}

/* Ensure workers exist. Returns 0 when threading is unavailable. The
 * lock-free fast path is why `started` is atomic (its declaration): every
 * large-table codec call lands here first. */
static int stc_pool_up(void) {
  int st = atomic_load_explicit(&g_pool.started, memory_order_acquire);
  if (st == 1) return 1;
  if (st < 0) return 0;
  stc_mutex_lock(&g_pool.mu);
  if (atomic_load_explicit(&g_pool.started, memory_order_relaxed) == 0) {
    int nt = stc_pool_threads();
    if (nt <= 1) {
      atomic_store_explicit(&g_pool.started, -1, memory_order_release);
    } else {
      pthread_atfork(NULL, NULL, stc_pool_child);
      int spawned = 0;
      for (int i = 0; i < nt - 1; i++) { /* submitter participates */
        pthread_t t;
        if (pthread_create(&t, NULL, stc_pool_worker, NULL) == 0) {
          pthread_detach(t);
          spawned++;
        }
      }
      g_pool.nworkers = spawned;
      atomic_store_explicit(&g_pool.started, spawned > 0 ? 1 : -1,
                            memory_order_release);
    }
  }
  int ok = atomic_load_explicit(&g_pool.started, memory_order_relaxed) == 1;
  stc_mutex_unlock(&g_pool.mu);
  return ok;
}

/* Run fn(ctx, seg) for seg in [0, nseg) across the pool; the caller works
 * too. Returns 1 if the job ran on the pool, 0 if the caller must run the
 * whole loop inline (pool busy / dead / tiny job). */
static int stc_pool_run(stc_seg_fn fn, void *ctx, int64_t nseg) {
  if (nseg < 2 || nseg >= (int64_t)1 << 32 || !stc_pool_up()) return 0;
  if (stc_mutex_trylock(&g_pool.job_mu) != 0) return 0;
  /* job_mu serializes submitters, so gen is ours to bump; the fields
   * publish under the agen seqlock: odd tag first (the acq_rel RMW pins
   * the stores AFTER it), fields + tagged counters, then the new even
   * tag LAST (release) — a worker whose two agen reads bracket a stable
   * even value observed exactly this job's field set. */
  g_pool.gen++;
  uint64_t gen = g_pool.gen; /* ours until job_mu is released */
  atomic_fetch_add_explicit(&g_pool.agen, 1, memory_order_acq_rel);
  atomic_store_explicit(&g_pool.fn, fn, memory_order_relaxed);
  atomic_store_explicit(&g_pool.ctx, ctx, memory_order_relaxed);
  atomic_store_explicit(&g_pool.nseg, nseg, memory_order_relaxed);
  atomic_store_explicit(&g_pool.afin, (uint64_t)(uint32_t)gen << 32,
                        memory_order_relaxed);
  /* generation-tagged chunk counter (index 0): any straggler still
   * holding the previous gen can no longer pop from it */
  atomic_store(&g_pool.next, (uint64_t)(uint32_t)gen << 32);
  atomic_store_explicit(&g_pool.agen, gen << 1, memory_order_release);
  /* wake sleepers only when there are any: the unlocked read can miss a
   * JUST-registering sleeper, whose 2 ms timedwait tick re-checks agen —
   * bounded lag on an idle->busy edge, zero mutex traffic when hot */
  if (g_pool.sleepers > 0) {
    stc_mutex_lock(&g_pool.mu);
    pthread_cond_broadcast(&g_pool.cv_job);
    stc_mutex_unlock(&g_pool.mu);
  }
  int64_t done = 0;
  for (;;) {
    int64_t s = stc_pool_pop(gen, nseg);
    if (s < 0) break;
    fn(ctx, s);
    done++;
  }
  /* completion: count our own chunks in (plain add — the tag is ours by
   * construction and counts can never carry into it: total <= nseg <
   * 2^32), then spin-watch the tagged word before falling back to the
   * condvar sleep — the tail chunk usually lands within a few us. */
  uint64_t fin_word =
      atomic_fetch_add(&g_pool.afin, (uint64_t)done) + (uint64_t)done;
  if ((int64_t)(fin_word & 0xffffffffu) < nseg) {
    int waited = 0;
    for (int i = 0; i < ST_SPIN_ITERS; i++) {
      if ((int64_t)(atomic_load_explicit(&g_pool.afin,
                                         memory_order_acquire) &
                    0xffffffffu) >= nseg) {
        waited = 1;
        break;
      }
      stc_cpu_relax();
    }
    if (!waited &&
        (int64_t)(atomic_load_explicit(&g_pool.afin, memory_order_acquire) &
                  0xffffffffu) < nseg) {
      atomic_store_explicit(&g_pool.sub_waiting, 1, memory_order_release);
      stc_mutex_lock(&g_pool.mu);
      while ((int64_t)(atomic_load_explicit(&g_pool.afin,
                                            memory_order_acquire) &
                       0xffffffffu) < nseg) {
        struct timespec ts;
        clock_gettime(CLOCK_REALTIME, &ts);
        ts.tv_nsec += 2000000; /* 2 ms tick: bounds the signal race */
        if (ts.tv_nsec >= 1000000000) {
          ts.tv_sec++;
          ts.tv_nsec -= 1000000000;
        }
        pthread_cond_timedwait(&g_pool.cv_done, &g_pool.mu.m, &ts);
      }
      stc_mutex_unlock(&g_pool.mu);
      atomic_store_explicit(&g_pool.sub_waiting, 0, memory_order_release);
    }
  }
  stc_mutex_unlock(&g_pool.job_mu);
  return 1;
}

/* A chunk is a word range [w0, w1) inside ONE leaf (never spans leaves —
 * each kernel body stays a single-leaf range loop). Fixed decomposition:
 * every leaf splits at ST_CHUNK_ELEMS boundaries of its own padded span. */
typedef struct {
  int64_t leaf, w0, w1;
} stc_chunk;

/* total padded elements + chunk count for a layout */
static int64_t stc_count_chunks(const int64_t *padded, int64_t n_leaves,
                                int64_t *out_total) {
  int64_t total = 0, nc = 0;
  for (int64_t i = 0; i < n_leaves; i++) {
    total += padded[i];
    nc += (padded[i] + ST_CHUNK_ELEMS - 1) / ST_CHUNK_ELEMS;
  }
  if (out_total) *out_total = total;
  return nc;
}

static void stc_build_chunks(const int64_t *padded, int64_t n_leaves,
                             stc_chunk *out) {
  int64_t k = 0;
  for (int64_t i = 0; i < n_leaves; i++) {
    int64_t nw = padded[i] / 32, cw = ST_CHUNK_ELEMS / 32;
    for (int64_t w0 = 0; w0 < nw; w0 += cw) {
      out[k].leaf = i;
      out[k].w0 = w0;
      out[k].w1 = w0 + cw < nw ? w0 + cw : nw;
      k++;
    }
    /* an empty leaf (padded == 0) contributes no chunks; partial outputs
     * for it are zero-filled by the wrappers */
  }
}
#else
#define ST_PAR_MIN_ELEMS ((int64_t)1 << 62)
#endif

/* Sender half for one leaf: sign-quantize + pack + error feedback, one fused
 * pass. bit = (r <= 0) — zero counts as negative (reference quirk Q3, kept:
 * converged elements oscillate within +/-scale). With s == 0 the leaf idles:
 * bits still record signs (matching the XLA/numpy tiers bit-for-bit) but the
 * residual is untouched. */
#ifdef ST_AVX512
/* Words whose 32 lanes are all live: two 16-lane compares produce the
 * bitmask directly; +/-s is the scale with the mask spliced into the IEEE
 * sign bit (exactly the scalar code's union trick, 16 lanes at a time).
 * Processes words [w0, min(w1, n/32)); returns the stopping word. */
ST_TARGET_AVX512
static int64_t quantize_leaf_avx512(const float *rin, float *rout, int64_t n,
                                    float s, uint32_t *words, int64_t w0,
                                    int64_t w1) {
  const __m512 vzero = _mm512_setzero_ps();
  const __m512i vs = _mm512_castps_si512(_mm512_set1_ps(s));
  const __m512i vsign = _mm512_set1_epi32((int32_t)0x80000000u);
  int64_t w = w0, wl = n / 32 < w1 ? n / 32 : w1;
  for (; w < wl; w++) {
    const float *p = rin + w * 32;
    float *q = rout + w * 32;
    __m512 v0 = _mm512_loadu_ps(p);
    __m512 v1 = _mm512_loadu_ps(p + 16);
    __mmask16 m0 = _mm512_cmp_ps_mask(v0, vzero, _CMP_LE_OQ);
    __mmask16 m1 = _mm512_cmp_ps_mask(v1, vzero, _CMP_LE_OQ);
    if (s > 0.0f) {
      __m512 d0 = _mm512_castsi512_ps(_mm512_mask_xor_epi32(vs, m0, vs, vsign));
      __m512 d1 = _mm512_castsi512_ps(_mm512_mask_xor_epi32(vs, m1, vs, vsign));
      _mm512_storeu_ps(q, _mm512_sub_ps(v0, d0));
      _mm512_storeu_ps(q + 16, _mm512_sub_ps(v1, d1));
    } else {
      _mm512_storeu_ps(q, v0);
      _mm512_storeu_ps(q + 16, v1);
    }
    words[w] = (uint32_t)m0 | ((uint32_t)m1 << 16);
  }
  return w;
}
#endif

/* words [w0, w1) of one leaf (w1 <= padded/32) */
ST_CLONES
static void quantize_leaf_range(const float *rin, float *rout, int64_t n,
                                float s, uint32_t *words, int64_t w0,
                                int64_t w1) {
  int64_t nw = w1;
  int64_t w = w0;
#ifdef ST_AVX512
  if (st_has_avx512())
    w = quantize_leaf_avx512(rin, rout, n, s, words, w0, w1);
#endif
  for (; w < nw; w++) {
    uint32_t bits = 0;
    int64_t base = w * 32;
    int64_t lim = n - base;
    if (lim > 32) lim = 32;
    if (s > 0.0f) {
      for (int64_t b = 0; b < lim; b++) {
        float v = rin[base + b];
        uint32_t neg = v <= 0.0f;
        bits |= neg << b;
        rout[base + b] = v - (neg ? -s : s);
      }
    } else {
      for (int64_t b = 0; b < lim; b++) {
        float v = rin[base + b];
        bits |= (uint32_t)(v <= 0.0f) << b;
        rout[base + b] = v;
      }
    }
    /* the caller hands a fresh output buffer: re-establish the all-zero
     * padding invariant on lanes past the live elements */
    for (int64_t b = (lim < 0 ? 0 : lim); b < 32; b++) rout[base + b] = 0.0f;
    words[w] = bits;
  }
}

#ifdef ST_AVX512
/* 16 floats/iter; squares/sums accumulate in 8-lane doubles, so the
 * result is a double-sum like the scalar path (order differs; double
 * accumulation makes the difference vanish below f32 rounding — the
 * tiers tolerate 1-ulp scale differences, see ops/codec_np.py).
 * Covers elements [j0, n) in 16-lane steps; returns the stopping element;
 * partials land in amax, ss, sabs. */
ST_TARGET_AVX512
static int64_t scale_partials_leaf_avx512(const float *p, int64_t n,
                                          double *amax, double *ss,
                                          double *sabs, int64_t j0) {
  const __m512i vabsmask = _mm512_set1_epi32(0x7FFFFFFF);
  __m512 vamax = _mm512_setzero_ps();
  __m512d vss0 = _mm512_setzero_pd(), vss1 = _mm512_setzero_pd();
  __m512d vsa0 = _mm512_setzero_pd(), vsa1 = _mm512_setzero_pd();
  int64_t j = j0;
  for (; j + 16 <= n; j += 16) {
    __m512 v = _mm512_loadu_ps(p + j);
    __m512 a = _mm512_castsi512_ps(
        _mm512_and_epi32(_mm512_castps_si512(v), vabsmask));
    vamax = _mm512_max_ps(vamax, a);
    __m512d lo = _mm512_cvtps_pd(_mm512_castps512_ps256(v));
    __m512d hi = _mm512_cvtps_pd(_mm512_extractf32x8_ps(v, 1));
    vss0 = _mm512_fmadd_pd(lo, lo, vss0);
    vss1 = _mm512_fmadd_pd(hi, hi, vss1);
    __m512d alo = _mm512_cvtps_pd(_mm512_castps512_ps256(a));
    __m512d ahi = _mm512_cvtps_pd(_mm512_extractf32x8_ps(a, 1));
    vsa0 = _mm512_add_pd(vsa0, alo);
    vsa1 = _mm512_add_pd(vsa1, ahi);
  }
  *amax = _mm512_reduce_max_ps(vamax);
  *ss = _mm512_reduce_add_pd(vss0) + _mm512_reduce_add_pd(vss1);
  *sabs = _mm512_reduce_add_pd(vsa0) + _mm512_reduce_add_pd(vsa1);
  return j;
}
#endif

/* Reduction partials of LIVE elements [e0, e1) of one leaf (e1 <= ns):
 * max|r|, sum(r^2), sum(|r|). Double accumulators make the raw sums
 * overflow-safe by construction (f32 max squared ~1.2e77 << DBL_MAX), where
 * the f32 tiers need the amax-normalization trick (quirk Q9 discussion in
 * ops/codec.compute_scale). The Python caller finishes the policy math. */
ST_CLONES
static void scale_partials_range(const float *p, int64_t e0, int64_t e1,
                                 double *out_amax, double *out_ss,
                                 double *out_sabs) {
  /* 4-way unrolled accumulators: breaks the serial FP dependency chain so
   * the adds pipeline (a single double accumulator costs ~4 cycles/elem) */
  double amax[4] = {0, 0, 0, 0}, ss[4] = {0, 0, 0, 0}, sabs[4] = {0, 0, 0, 0};
  int64_t j = e0;
#ifdef ST_AVX512
  if (st_has_avx512())
    j = scale_partials_leaf_avx512(p, e1, &amax[0], &ss[0], &sabs[0], e0);
#endif
  for (; j + 4 <= e1; j += 4) {
    for (int u = 0; u < 4; u++) {
      double v = p[j + u];
      double a = v < 0 ? -v : v;
      if (a > amax[u]) amax[u] = a;
      ss[u] += v * v;
      sabs[u] += a;
    }
  }
  for (; j < e1; j++) {
    double v = p[j];
    double a = v < 0 ? -v : v;
    if (a > amax[0]) amax[0] = a;
    ss[0] += v * v;
    sabs[0] += a;
  }
  double am = amax[0];
  for (int u = 1; u < 4; u++)
    if (amax[u] > am) am = amax[u];
  *out_amax = am;
  *out_ss = ss[0] + ss[1] + ss[2] + ss[3];
  *out_sabs = sabs[0] + sabs[1] + sabs[2] + sabs[3];
}

#ifdef ST_POOL
/* Per-leaf reduction of per-chunk partials, in chunk order: the grouping is
 * fixed by the layout (stc_build_chunks), so the result is identical for
 * every thread count. */
static void reduce_chunk_partials(const stc_chunk *chunks, int64_t nc,
                                  int64_t n_leaves, const double *camax,
                                  const double *css, const double *csabs,
                                  double *out_amax, double *out_ss,
                                  double *out_sabs) {
  for (int64_t i = 0; i < n_leaves; i++) {
    out_amax[i] = 0;
    out_ss[i] = 0;
    out_sabs[i] = 0;
  }
  for (int64_t c = 0; c < nc; c++) {
    int64_t i = chunks[c].leaf;
    if (camax[c] > out_amax[i]) out_amax[i] = camax[c];
    out_ss[i] += css[c];
    out_sabs[i] += csabs[c];
  }
}

typedef struct {
  const float *r;
  const int64_t *off, *ns;
  const stc_chunk *chunks;
  double *camax, *css, *csabs;
} sp_ctx;

static void scale_partials_seg(void *vctx, int64_t c) {
  sp_ctx *x = (sp_ctx *)vctx;
  const stc_chunk *ch = &x->chunks[c];
  int64_t n = x->ns[ch->leaf];
  int64_t e0 = ch->w0 * 32, e1 = ch->w1 * 32;
  if (e1 > n) e1 = n;
  if (e0 > e1) e0 = e1;
  scale_partials_range(x->r + x->off[ch->leaf], e0, e1, &x->camax[c],
                       &x->css[c], &x->csabs[c]);
}
#endif

EXPORT void stc_scale_partials(const float *r, const int64_t *off,
                               const int64_t *ns, int64_t n_leaves,
                               double *out_amax, double *out_ss,
                               double *out_sabs) {
#ifdef ST_POOL
  int64_t total = 0;
  int64_t nc = 0;
  /* chunk over round32(ns) word spans — identical decomposition to the
   * other ops when padded == round32(ns), which the table layout
   * guarantees, so fused and standalone partials group alike */
  for (int64_t i = 0; i < n_leaves; i++) total += ns[i];
  if (total >= ST_PAR_MIN_ELEMS) {
    /* build chunks over round32(ns) per leaf */
    int64_t cap = 0;
    for (int64_t i = 0; i < n_leaves; i++)
      cap += ((ns[i] + 31) / 32 * 32 + ST_CHUNK_ELEMS - 1) / ST_CHUNK_ELEMS;
    stc_chunk *chunks = (stc_chunk *)malloc((size_t)cap * sizeof(stc_chunk));
    double *pbuf = (double *)malloc((size_t)cap * 3 * sizeof(double));
    if (chunks && pbuf) {
      int64_t k = 0;
      for (int64_t i = 0; i < n_leaves; i++) {
        int64_t nw = (ns[i] + 31) / 32, cw = ST_CHUNK_ELEMS / 32;
        for (int64_t w0 = 0; w0 < nw; w0 += cw) {
          chunks[k].leaf = i;
          chunks[k].w0 = w0;
          chunks[k].w1 = w0 + cw < nw ? w0 + cw : nw;
          k++;
        }
      }
      nc = k;
      sp_ctx x = {r, off, ns, chunks, pbuf, pbuf + nc, pbuf + 2 * nc};
      if (stc_pool_run(scale_partials_seg, &x, nc)) {
        reduce_chunk_partials(chunks, nc, n_leaves, x.camax, x.css, x.csabs,
                              out_amax, out_ss, out_sabs);
        free(chunks);
        free(pbuf);
        return;
      }
    }
    free(chunks);
    free(pbuf);
  }
#endif
  for (int64_t i = 0; i < n_leaves; i++)
    scale_partials_range(r + off[i], 0, ns[i], &out_amax[i], &out_ss[i],
                         &out_sabs[i]);
}

#ifdef ST_POOL
typedef struct {
  const float *rin;
  float *rout;
  const int64_t *off, *ns;
  const float *scales;
  uint32_t *words;
  const stc_chunk *chunks;
} qz_ctx;

static void quantize_seg(void *vctx, int64_t c) {
  qz_ctx *x = (qz_ctx *)vctx;
  const stc_chunk *ch = &x->chunks[c];
  int64_t i = ch->leaf;
  quantize_leaf_range(x->rin + x->off[i], x->rout + x->off[i], x->ns[i],
                      x->scales[i], x->words + x->off[i] / 32, ch->w0, ch->w1);
}
#endif

/* Functional form — reads rin, writes rout (the Python tier's update
 * discipline is replace-not-mutate, so writing to a fresh output buffer
 * saves the 4-byte-per-element input copy an in-place API would force). */
EXPORT void stc_quantize(const float *rin, float *rout, const int64_t *off,
                         const int64_t *ns, const int64_t *padded,
                         int64_t n_leaves, const float *scales,
                         uint32_t *words) {
#ifdef ST_POOL
  int64_t total = 0;
  int64_t nc = stc_count_chunks(padded, n_leaves, &total);
  if (total >= ST_PAR_MIN_ELEMS) {
    stc_chunk *chunks = (stc_chunk *)malloc((size_t)nc * sizeof(stc_chunk));
    if (chunks) {
      stc_build_chunks(padded, n_leaves, chunks);
      qz_ctx x = {rin, rout, off, ns, scales, words, chunks};
      int ran = stc_pool_run(quantize_seg, &x, nc);
      free(chunks);
      if (ran) return;
    }
  }
#endif
  for (int64_t i = 0; i < n_leaves; i++) {
    quantize_leaf_range(rin + off[i], rout + off[i], ns[i], scales[i],
                        words + off[i] / 32, 0, padded[i] / 32);
  }
}

#ifdef ST_AVX512
/* The packed word IS two __mmask16s: splice each bit into the IEEE sign
 * of a broadcast s (bit set -> -s, reference src/sharedtensor.c:109)
 * and accumulate, 16 lanes per op. Covers whole words [w0, full);
 * returns the stopping word. */
ST_TARGET_AVX512
static int64_t accumulate_leaf_avx512(float *d, const uint32_t *w,
                                      int64_t full, float s, int64_t w0) {
  const __m512i vs = _mm512_castps_si512(_mm512_set1_ps(s));
  const __m512i vsign = _mm512_set1_epi32((int32_t)0x80000000u);
  int64_t k = w0;
  for (; k < full; k++) {
    uint32_t bits = w[k];
    float *dd = d + k * 32;
    __mmask16 m0 = (__mmask16)bits;
    __mmask16 m1 = (__mmask16)(bits >> 16);
    __m512 d0 = _mm512_castsi512_ps(_mm512_mask_xor_epi32(vs, m0, vs, vsign));
    __m512 d1 = _mm512_castsi512_ps(_mm512_mask_xor_epi32(vs, m1, vs, vsign));
    _mm512_storeu_ps(dd, _mm512_add_ps(_mm512_loadu_ps(dd), d0));
    _mm512_storeu_ps(dd + 16, _mm512_add_ps(_mm512_loadu_ps(dd + 16), d1));
  }
  return k;
}
#endif

#ifdef ST_AVX512
/* Fused quantize + next-frame partials: the burst sender needs the NEW
 * residual's scale partials for frame k+1, and they are free to accumulate
 * while frame k's residual values are still in registers — one memory pass
 * instead of quantize-then-rescan (the two-pass shape costs ~40% of the
 * engine's per-frame time at 1 Mi). Covers words [w0, min(w1, n/32));
 * returns the stopping word. */
ST_TARGET_AVX512
static int64_t quantize_partials_leaf_avx512(const float *rin, float *rout,
                                             int64_t n, float s,
                                             uint32_t *words, double *amax,
                                             double *ss, double *sabs,
                                             int64_t w0, int64_t w1) {
  const __m512 vzero = _mm512_setzero_ps();
  const __m512i vs = _mm512_castps_si512(_mm512_set1_ps(s));
  const __m512i vsign = _mm512_set1_epi32((int32_t)0x80000000u);
  const __m512i vabsmask = _mm512_set1_epi32(0x7FFFFFFF);
  __m512 vamax = _mm512_setzero_ps();
  __m512d vss0 = _mm512_setzero_pd(), vss1 = _mm512_setzero_pd();
  __m512d vsa0 = _mm512_setzero_pd(), vsa1 = _mm512_setzero_pd();
  int64_t w = w0, wl = n / 32 < w1 ? n / 32 : w1;
  for (; w < wl; w++) {
    const float *p = rin + w * 32;
    float *q = rout + w * 32;
    __m512 v0 = _mm512_loadu_ps(p);
    __m512 v1 = _mm512_loadu_ps(p + 16);
    __mmask16 m0 = _mm512_cmp_ps_mask(v0, vzero, _CMP_LE_OQ);
    __mmask16 m1 = _mm512_cmp_ps_mask(v1, vzero, _CMP_LE_OQ);
    __m512 r0 = v0, r1 = v1;
    if (s > 0.0f) {
      __m512 d0 = _mm512_castsi512_ps(_mm512_mask_xor_epi32(vs, m0, vs, vsign));
      __m512 d1 = _mm512_castsi512_ps(_mm512_mask_xor_epi32(vs, m1, vs, vsign));
      r0 = _mm512_sub_ps(v0, d0);
      r1 = _mm512_sub_ps(v1, d1);
    }
    _mm512_storeu_ps(q, r0);
    _mm512_storeu_ps(q + 16, r1);
    words[w] = (uint32_t)m0 | ((uint32_t)m1 << 16);
    /* partials of the residual just written (scale_partials_leaf_avx512's
     * arithmetic, fused here) */
    __m512 a0 = _mm512_castsi512_ps(
        _mm512_and_epi32(_mm512_castps_si512(r0), vabsmask));
    __m512 a1 = _mm512_castsi512_ps(
        _mm512_and_epi32(_mm512_castps_si512(r1), vabsmask));
    vamax = _mm512_max_ps(vamax, _mm512_max_ps(a0, a1));
    __m512d lo0 = _mm512_cvtps_pd(_mm512_castps512_ps256(r0));
    __m512d hi0 = _mm512_cvtps_pd(_mm512_extractf32x8_ps(r0, 1));
    __m512d lo1 = _mm512_cvtps_pd(_mm512_castps512_ps256(r1));
    __m512d hi1 = _mm512_cvtps_pd(_mm512_extractf32x8_ps(r1, 1));
    vss0 = _mm512_fmadd_pd(lo0, lo0, vss0);
    vss1 = _mm512_fmadd_pd(hi0, hi0, vss1);
    vss0 = _mm512_fmadd_pd(lo1, lo1, vss0);
    vss1 = _mm512_fmadd_pd(hi1, hi1, vss1);
    vsa0 = _mm512_add_pd(
        vsa0, _mm512_cvtps_pd(_mm512_castps512_ps256(a0)));
    vsa1 = _mm512_add_pd(
        vsa1, _mm512_cvtps_pd(_mm512_extractf32x8_ps(a0, 1)));
    vsa0 = _mm512_add_pd(
        vsa0, _mm512_cvtps_pd(_mm512_castps512_ps256(a1)));
    vsa1 = _mm512_add_pd(
        vsa1, _mm512_cvtps_pd(_mm512_extractf32x8_ps(a1, 1)));
  }
  *amax = _mm512_reduce_max_ps(vamax);
  *ss = _mm512_reduce_add_pd(vss0) + _mm512_reduce_add_pd(vss1);
  *sabs = _mm512_reduce_add_pd(vsa0) + _mm512_reduce_add_pd(vsa1);
  return w;
}
#endif

/* Quantize + new-residual partials for words [w0, w1) of one leaf (the
 * fused body of stc_quantize_ef_partials). */
ST_CLONES
static void quantize_partials_range(const float *p, float *q, int64_t n,
                                    float s, uint32_t *wp, int64_t w0,
                                    int64_t w1, double *out_amax,
                                    double *out_ss, double *out_sabs) {
  double amax = 0, ssum = 0, sabs = 0;
  int64_t w = w0;
#ifdef ST_AVX512
  if (st_has_avx512())
    w = quantize_partials_leaf_avx512(p, q, n, s, wp, &amax, &ssum, &sabs, w0,
                                      w1);
#endif
  for (; w < w1; w++) {
    uint32_t bits = 0;
    int64_t base = w * 32;
    int64_t lim = n - base;
    if (lim > 32) lim = 32;
    for (int64_t b = 0; b < (lim < 0 ? 0 : lim); b++) {
      float v = p[base + b];
      uint32_t neg = v <= 0.0f;
      bits |= neg << b;
      float r = s > 0.0f ? v - (neg ? -s : s) : v;
      q[base + b] = r;
      double a = r < 0 ? -(double)r : (double)r;
      if (a > amax) amax = a;
      ssum += (double)r * (double)r;
      sabs += a;
    }
    for (int64_t b = (lim < 0 ? 0 : lim); b < 32; b++) q[base + b] = 0.0f;
    wp[w] = bits;
  }
  *out_amax = amax;
  *out_ss = ssum;
  *out_sabs = sabs;
}

#ifdef ST_POOL
typedef struct {
  const float *rin;
  float *rout;
  const int64_t *off, *ns;
  const float *scales;
  uint32_t *words;
  const stc_chunk *chunks;
  double *camax, *css, *csabs;
} qzp_ctx;

static void quantize_partials_seg(void *vctx, int64_t c) {
  qzp_ctx *x = (qzp_ctx *)vctx;
  const stc_chunk *ch = &x->chunks[c];
  int64_t i = ch->leaf;
  quantize_partials_range(x->rin + x->off[i], x->rout + x->off[i], x->ns[i],
                          x->scales[i], x->words + x->off[i] / 32, ch->w0,
                          ch->w1, &x->camax[c], &x->css[c], &x->csabs[c]);
}
#endif

/* Sender step + NEXT frame's scale partials, one fused pass per leaf (see
 * quantize_partials_leaf_avx512). Partials are per-leaf overwrites like
 * stc_scale_partials; live lanes only. Semantics of the quantize half are
 * identical to stc_quantize. */
EXPORT void stc_quantize_ef_partials(
    const float *rin, float *rout, const int64_t *off, const int64_t *ns,
    const int64_t *padded, int64_t n_leaves, const float *scales,
    uint32_t *words, double *out_amax, double *out_ss, double *out_sabs) {
#ifdef ST_POOL
  int64_t total = 0;
  int64_t nc = stc_count_chunks(padded, n_leaves, &total);
  if (total >= ST_PAR_MIN_ELEMS) {
    stc_chunk *chunks = (stc_chunk *)malloc((size_t)nc * sizeof(stc_chunk));
    double *pbuf = (double *)malloc((size_t)nc * 3 * sizeof(double));
    if (chunks && pbuf) {
      stc_build_chunks(padded, n_leaves, chunks);
      qzp_ctx x = {rin,    rout,  off,  ns,         scales,
                   words,  chunks, pbuf, pbuf + nc, pbuf + 2 * nc};
      if (stc_pool_run(quantize_partials_seg, &x, nc)) {
        reduce_chunk_partials(chunks, nc, n_leaves, x.camax, x.css, x.csabs,
                              out_amax, out_ss, out_sabs);
        free(chunks);
        free(pbuf);
        return;
      }
    }
    free(chunks);
    free(pbuf);
  }
#endif
  for (int64_t i = 0; i < n_leaves; i++) {
    quantize_partials_range(rin + off[i], rout + off[i], ns[i], scales[i],
                            words + off[i] / 32, 0, padded[i] / 32,
                            &out_amax[i], &out_ss[i], &out_sabs[i]);
  }
}

/* delta += s*(1-2*bit) over words [w0, w1) of one leaf; the partial word
 * (if any) is handled when it falls inside the range. */
ST_CLONES
static void accumulate_delta_range(float *d, const uint32_t *w, int64_t n,
                                   float s, int64_t w0, int64_t w1) {
  int64_t full = n / 32; /* whole words: branch-free, vectorizable */
  if (full > w1) full = w1;
  int64_t k = w0;
#ifdef ST_AVX512
  if (st_has_avx512()) k = accumulate_leaf_avx512(d, w, full, s, w0);
#endif
  for (; k < full; k++) {
    uint32_t bits = w[k];
    float *dd = d + k * 32;
    float signs[32];
    /* +/-s differ only in the IEEE sign bit: splice the codec bit in */
    for (int b = 0; b < 32; b++) {
      union { float f; uint32_t u; } u;
      u.f = s;
      u.u |= ((bits >> b) & 1u) << 31;
      signs[b] = u.f;
    }
    for (int b = 0; b < 32; b++) dd[b] += signs[b];
  }
  if (n % 32 && n / 32 >= w0 && n / 32 < w1) {
    int64_t base = (n / 32) * 32;
    uint32_t bits = w[n / 32];
    for (int64_t b = 0; b < n - base; b++) {
      d[base + b] += ((bits >> b) & 1u) ? -s : s;
    }
  }
}

#ifdef ST_POOL
typedef struct {
  float *delta;
  const int64_t *off, *ns;
  const float *scales;
  const uint32_t *words;
  const stc_chunk *chunks;
} ad_ctx;

static void accumulate_delta_seg(void *vctx, int64_t c) {
  ad_ctx *x = (ad_ctx *)vctx;
  const stc_chunk *ch = &x->chunks[c];
  int64_t i = ch->leaf;
  float s = x->scales[i];
  if (s == 0.0f) return;
  accumulate_delta_range(x->delta + x->off[i], x->words + x->off[i] / 32,
                         x->ns[i], s, ch->w0, ch->w1);
}
#endif

/* Receiver half: accumulate K frames' deltas into delta[total]
 * (delta += s * (1 - 2*bit), reference src/sharedtensor.c:109), then the
 * caller adds delta to each target array. Splitting accumulate/apply keeps
 * the per-array work to one add pass regardless of K. */
EXPORT void stc_accumulate_delta(float *delta, const int64_t *off,
                                 const int64_t *ns, const int64_t *padded,
                                 int64_t n_leaves, const float *scales,
                                 const uint32_t *words) {
#ifdef ST_POOL
  if (padded) {
    int64_t total = 0;
    int64_t nc = stc_count_chunks(padded, n_leaves, &total);
    if (total >= ST_PAR_MIN_ELEMS) {
      stc_chunk *chunks = (stc_chunk *)malloc((size_t)nc * sizeof(stc_chunk));
      if (chunks) {
        stc_build_chunks(padded, n_leaves, chunks);
        ad_ctx x = {delta, off, ns, scales, words, chunks};
        int ran = stc_pool_run(accumulate_delta_seg, &x, nc);
        free(chunks);
        if (ran) return;
      }
    }
  }
#endif
  (void)padded;
  for (int64_t i = 0; i < n_leaves; i++) {
    float s = scales[i];
    if (s == 0.0f) continue;
    accumulate_delta_range(delta + off[i], words + off[i] / 32, ns[i], s, 0,
                           ns[i] / 32 + (ns[i] % 32 ? 1 : 0));
  }
}

ST_CLONES
static void add_to_range(float *out, const float *a, const float *delta,
                         int64_t i0, int64_t i1) {
  for (int64_t i = i0; i < i1; i++) {
    float s = a[i] + delta[i];
    s = s > 3.0e38f ? 3.0e38f : s;
    s = s < -3.0e38f ? -3.0e38f : s;
    out[i] = s;
  }
}

#ifdef ST_POOL
/* flat elementwise split: fixed ST_CHUNK_ELEMS ranges over [0, total) */
typedef struct {
  float *out;
  const float *a, *b;
  int64_t total;
  int op; /* 0 = add_to, 1 = accumulate_update */
} ew_ctx;

static void elementwise_seg(void *vctx, int64_t c);

static int elementwise_par(int op, float *out, const float *a, const float *b,
                           int64_t total) {
  if (total < ST_PAR_MIN_ELEMS) return 0;
  ew_ctx x = {out, a, b, total, op};
  int64_t nseg = (total + ST_CHUNK_ELEMS - 1) / ST_CHUNK_ELEMS;
  return stc_pool_run(elementwise_seg, &x, nseg);
}
#endif

/* values[i] += delta[i] for one target array (live lanes only — padding in
 * both is 0 by invariant, so a full-width add preserves it). Result clamped
 * to +/-3e38 like every other state-mutating path (ops/codec.SAT: no
 * absorbing inf/NaN state, any tier). Branchless min/max — vectorizes. */
EXPORT void stc_add_inplace(float *values, const float *delta, int64_t total) {
#ifdef ST_POOL
  if (elementwise_par(0, values, values, delta, total)) return;
#endif
  add_to_range(values, values, delta, 0, total);
}

/* out[i] = clip(a[i] + delta[i]): the functional-update form of
 * stc_add_inplace. One pass instead of copy-then-add — at table sizes past
 * LLC the host tier is memory-bandwidth-bound and the extra copy pass was
 * ~1/3 of the apply cost (measured at 16 Mi elements). */
EXPORT void stc_add_to(float *out, const float *a, const float *delta,
                       int64_t total) {
#ifdef ST_POOL
  if (elementwise_par(0, out, a, delta, total)) return;
#endif
  add_to_range(out, a, delta, 0, total);
}

#ifdef ST_AVX512
ST_TARGET_AVX512
static int64_t apply_leaf_avx512(const float *in, float *out,
                                 const uint32_t *w, int64_t full, float s,
                                 int64_t w0) {
  const __m512i vs = _mm512_castps_si512(_mm512_set1_ps(s));
  const __m512i vsign = _mm512_set1_epi32((int32_t)0x80000000u);
  const __m512 vmax = _mm512_set1_ps(3.0e38f);
  const __m512 vmin = _mm512_set1_ps(-3.0e38f);
  int64_t k = w0;
  for (; k < full; k++) {
    uint32_t bits = w[k];
    const float *pp = in + k * 32;
    float *qq = out + k * 32;
    __mmask16 m0 = (__mmask16)bits;
    __mmask16 m1 = (__mmask16)(bits >> 16);
    __m512 d0 = _mm512_castsi512_ps(_mm512_mask_xor_epi32(vs, m0, vs, vsign));
    __m512 d1 = _mm512_castsi512_ps(_mm512_mask_xor_epi32(vs, m1, vs, vsign));
    __m512 r0 = _mm512_add_ps(_mm512_loadu_ps(pp), d0);
    __m512 r1 = _mm512_add_ps(_mm512_loadu_ps(pp + 16), d1);
    r0 = _mm512_max_ps(_mm512_min_ps(r0, vmax), vmin);
    r1 = _mm512_max_ps(_mm512_min_ps(r1, vmax), vmin);
    _mm512_storeu_ps(qq, r0);
    _mm512_storeu_ps(qq + 16, r1);
  }
  return k;
}
#endif

/* out = clip(in + s*(1-2*bit)) over words [w0, w1) of one leaf; padding
 * lanes inside the range are copied verbatim (0 by invariant). */
ST_CLONES
static void apply_frame_range(const float *in, float *out, const uint32_t *w,
                              int64_t n, int64_t pad, float s, int64_t w0,
                              int64_t w1) {
  if (s == 0.0f) { /* idle leaf: pure copy */
    memcpy(out + w0 * 32, in + w0 * 32, (size_t)(w1 - w0) * 32 * sizeof(float));
    return;
  }
  int64_t full = n / 32;
  if (full > w1) full = w1;
  int64_t k = w0;
#ifdef ST_AVX512
  if (st_has_avx512()) k = apply_leaf_avx512(in, out, w, full, s, w0);
#endif
  for (; k < full; k++) {
    uint32_t bits = w[k];
    for (int b = 0; b < 32; b++) {
      float v = in[k * 32 + b] + (((bits >> b) & 1u) ? -s : s);
      v = v > 3.0e38f ? 3.0e38f : v;
      v = v < -3.0e38f ? -3.0e38f : v;
      out[k * 32 + b] = v;
    }
  }
  int64_t base = full * 32;
  if (n % 32 && n / 32 >= w0 && n / 32 < w1) {
    base = (n / 32) * 32;
    uint32_t bits = w[n / 32];
    for (int64_t b = 0; b < n - base; b++) {
      float v = in[base + b] + (((bits >> b) & 1u) ? -s : s);
      v = v > 3.0e38f ? 3.0e38f : v;
      v = v < -3.0e38f ? -3.0e38f : v;
      out[base + b] = v;
    }
    for (int64_t b = n - base; b < 32 && base + b < pad; b++)
      out[base + b] = in[base + b];
    base += 32;
  }
  /* trailing pure-padding words of THIS range only (a chunk past the live
   * data must not copy below its own w0 — that is another chunk's region) */
  if (base < w0 * 32) base = w0 * 32;
  int64_t end = w1 * 32;
  if (base < end && base < pad) {
    int64_t stop = end < pad ? end : pad;
    if (stop > base)
      memcpy(out + base, in + base, (size_t)(stop - base) * sizeof(float));
  }
}

#ifdef ST_POOL
typedef struct {
  const float *vin;
  float *vout;
  const int64_t *off, *ns, *padded;
  const float *scales;
  const uint32_t *words;
  const stc_chunk *chunks;
} ap_ctx;

static void apply_frame_seg(void *vctx, int64_t c) {
  ap_ctx *x = (ap_ctx *)vctx;
  const stc_chunk *ch = &x->chunks[c];
  int64_t i = ch->leaf;
  apply_frame_range(x->vin + x->off[i], x->vout + x->off[i],
                    x->words + x->off[i] / 32, x->ns[i], x->padded[i],
                    x->scales[i], ch->w0, ch->w1);
}
#endif

/* Fully fused single-frame apply: out = clip(in + s*(1-2*bit)) in ONE pass,
 * no delta buffer, no copy — the K=1 receive path (the common case: one
 * incoming frame applied to values + each other link's residual). Padding
 * lanes beyond ns[i] are copied verbatim (0 by invariant). */
EXPORT void stc_apply_frame(const float *vin, float *vout, const int64_t *off,
                            const int64_t *ns, const int64_t *padded,
                            int64_t n_leaves, const float *scales,
                            const uint32_t *words) {
#ifdef ST_POOL
  int64_t total = 0;
  int64_t nc = stc_count_chunks(padded, n_leaves, &total);
  if (total >= ST_PAR_MIN_ELEMS) {
    stc_chunk *chunks = (stc_chunk *)malloc((size_t)nc * sizeof(stc_chunk));
    if (chunks) {
      stc_build_chunks(padded, n_leaves, chunks);
      ap_ctx x = {vin, vout, off, ns, padded, scales, words, chunks};
      int ran = stc_pool_run(apply_frame_seg, &x, nc);
      free(chunks);
      if (ran) return;
    }
  }
#endif
  for (int64_t i = 0; i < n_leaves; i++) {
    apply_frame_range(vin + off[i], vout + off[i], words + off[i] / 32, ns[i],
                      padded[i], scales[i], 0, padded[i] / 32);
  }
}

ST_CLONES
static void accumulate_update_range(float *a, const float *u, int64_t i0,
                                    int64_t i1) {
  for (int64_t i = i0; i < i1; i++) {
    float x = u[i];
    if (x != x) x = 0.0f; /* NaN */
    if (x > 3.0e38f) x = 3.0e38f;
    if (x < -3.0e38f) x = -3.0e38f;
    float s = a[i] + x;
    if (s > 3.0e38f) s = 3.0e38f;
    if (s < -3.0e38f) s = -3.0e38f;
    a[i] = s;
  }
}

#ifdef ST_POOL
static void elementwise_seg(void *vctx, int64_t c) {
  ew_ctx *x = (ew_ctx *)vctx;
  int64_t i0 = c * ST_CHUNK_ELEMS;
  int64_t i1 = i0 + ST_CHUNK_ELEMS;
  if (i1 > x->total) i1 = x->total;
  if (x->op == 0)
    add_to_range(x->out, x->a, x->b, i0, i1);
  else
    accumulate_update_range(x->out, x->b, i0, i1);
}
#endif

/* Local additive update, sanitized (quirk Q9 fix — one NaN in the reference
 * poisons every replica through the flood): u is pre-masked by the caller;
 * NaN -> 0, +/-inf and sums clamped to +/-3e38. */
EXPORT void stc_accumulate_update(float *a, const float *u, int64_t total) {
#ifdef ST_POOL
  if (elementwise_par(1, a, a, u, total)) return;
#endif
  accumulate_update_range(a, u, 0, total);
}

#ifdef ST_AVX512
/* clip(a + sanitize(u)) + result partials, 16 lanes at a time over
 * elements [j0, j0+16k) (k maximal with j0+16k <= n). The scalar loop's
 * NaN/clamp/partials mix defeats autovectorization (measured 1.2 GB/s vs
 * 12.5 for the partial-less op at 16 Mi — a 10x cliff on the add path);
 * this kernel restores it. Returns the stopping element. */
ST_TARGET_AVX512
static int64_t accumulate_update_leaf_avx512(float *op, const float *ap,
                                             const float *up, int64_t n,
                                             int64_t j0, int do_part,
                                             double *amax, double *ss,
                                             double *sabs) {
  const __m512 vmax = _mm512_set1_ps(3.0e38f);
  const __m512 vmin = _mm512_set1_ps(-3.0e38f);
  const __m512i vabsmask = _mm512_set1_epi32(0x7FFFFFFF);
  __m512 vamax = _mm512_setzero_ps();
  __m512d vss0 = _mm512_setzero_pd(), vss1 = _mm512_setzero_pd();
  __m512d vsa0 = _mm512_setzero_pd(), vsa1 = _mm512_setzero_pd();
  int64_t j = j0;
  for (; j + 16 <= n; j += 16) {
    __m512 u = _mm512_loadu_ps(up + j);
    __mmask16 ord = _mm512_cmp_ps_mask(u, u, _CMP_ORD_Q);
    u = _mm512_maskz_mov_ps(ord, u); /* NaN -> 0 */
    u = _mm512_max_ps(_mm512_min_ps(u, vmax), vmin);
    __m512 s = _mm512_add_ps(_mm512_loadu_ps(ap + j), u);
    s = _mm512_max_ps(_mm512_min_ps(s, vmax), vmin);
    _mm512_storeu_ps(op + j, s);
    if (do_part) {
      __m512 a = _mm512_castsi512_ps(
          _mm512_and_epi32(_mm512_castps_si512(s), vabsmask));
      vamax = _mm512_max_ps(vamax, a);
      __m512d lo = _mm512_cvtps_pd(_mm512_castps512_ps256(s));
      __m512d hi = _mm512_cvtps_pd(_mm512_extractf32x8_ps(s, 1));
      vss0 = _mm512_fmadd_pd(lo, lo, vss0);
      vss1 = _mm512_fmadd_pd(hi, hi, vss1);
      vsa0 = _mm512_add_pd(vsa0, _mm512_cvtps_pd(_mm512_castps512_ps256(a)));
      vsa1 = _mm512_add_pd(vsa1,
                           _mm512_cvtps_pd(_mm512_extractf32x8_ps(a, 1)));
    }
  }
  if (do_part) {
    *amax = _mm512_reduce_max_ps(vamax);
    *ss = _mm512_reduce_add_pd(vss0) + _mm512_reduce_add_pd(vss1);
    *sabs = _mm512_reduce_add_pd(vsa0) + _mm512_reduce_add_pd(vsa1);
  }
  return j;
}
#endif

/* out = clip(a + sanitize(u)) on live lanes of elements [e0, e1) of one
 * leaf (e0/e1 in padded coordinates); padding lanes in range copy from a.
 * Optional partials of the RESULT (live lanes in range) — fusing them here
 * makes a sender-side scale scan free whenever an add() already has to
 * traverse the residual (stengine.cpp partials cache). */
ST_CLONES
static void accumulate_update_to_range(float *op, const float *ap,
                                       const float *up, int64_t n, int64_t pad,
                                       int64_t e0, int64_t e1, double *out_amax,
                                       double *out_ss, double *out_sabs) {
  double amax = 0, ssum = 0, sabs = 0;
  int64_t live = n < e1 ? n : e1;
  int64_t j = e0;
#ifdef ST_AVX512
  if (st_has_avx512() && j < live) {
    double a2 = 0, s2 = 0, b2 = 0;
    j = accumulate_update_leaf_avx512(op, ap, up, live, j,
                                      out_amax != NULL, &a2, &s2, &b2);
    if (out_amax) {
      amax = a2;
      ssum = s2;
      sabs = b2;
    }
  }
#endif
  for (; j < live; j++) {
    float x = up[j];
    if (x != x) x = 0.0f; /* NaN */
    if (x > 3.0e38f) x = 3.0e38f;
    if (x < -3.0e38f) x = -3.0e38f;
    float s = ap[j] + x;
    if (s > 3.0e38f) s = 3.0e38f;
    if (s < -3.0e38f) s = -3.0e38f;
    op[j] = s;
    if (out_amax) {
      double d = s < 0 ? -(double)s : (double)s;
      if (d > amax) amax = d;
      ssum += (double)s * (double)s;
      sabs += d;
    }
  }
  int64_t cs = n > e0 ? n : e0;
  if (cs < e1 && cs < pad) {
    int64_t stop = e1 < pad ? e1 : pad;
    if (stop > cs)
      memcpy(op + cs, ap + cs, (size_t)(stop - cs) * sizeof(float));
  }
  if (out_amax) {
    *out_amax = amax;
    *out_ss = ssum;
    *out_sabs = sabs;
  }
}

#ifdef ST_POOL
typedef struct {
  float *vout;
  const float *a, *u;
  const int64_t *off, *ns, *padded;
  const stc_chunk *chunks;
  double *camax, *css, *csabs; /* NULL when no partials requested */
} au_ctx;

static void accumulate_update_to_seg(void *vctx, int64_t c) {
  au_ctx *x = (au_ctx *)vctx;
  const stc_chunk *ch = &x->chunks[c];
  int64_t i = ch->leaf;
  accumulate_update_to_range(
      x->vout + x->off[i], x->a + x->off[i], x->u + x->off[i], x->ns[i],
      x->padded[i], ch->w0 * 32, ch->w1 * 32,
      x->camax ? &x->camax[c] : NULL, x->camax ? &x->css[c] : NULL,
      x->camax ? &x->csabs[c] : NULL);
}
#endif

static void accumulate_update_to_impl(float *vout, const float *a,
                                      const float *u, const int64_t *off,
                                      const int64_t *ns, const int64_t *padded,
                                      int64_t n_leaves, double *out_amax,
                                      double *out_ss, double *out_sabs) {
#ifdef ST_POOL
  int64_t total = 0;
  int64_t nc = stc_count_chunks(padded, n_leaves, &total);
  if (total >= ST_PAR_MIN_ELEMS) {
    stc_chunk *chunks = (stc_chunk *)malloc((size_t)nc * sizeof(stc_chunk));
    double *pbuf =
        out_amax ? (double *)malloc((size_t)nc * 3 * sizeof(double)) : NULL;
    if (chunks && (!out_amax || pbuf)) {
      stc_build_chunks(padded, n_leaves, chunks);
      au_ctx x = {vout,   a,
                  u,      off,
                  ns,     padded,
                  chunks, pbuf,
                  pbuf ? pbuf + nc : NULL, pbuf ? pbuf + 2 * nc : NULL};
      if (stc_pool_run(accumulate_update_to_seg, &x, nc)) {
        if (out_amax)
          reduce_chunk_partials(chunks, nc, n_leaves, x.camax, x.css, x.csabs,
                                out_amax, out_ss, out_sabs);
        free(chunks);
        free(pbuf);
        return;
      }
    }
    free(chunks);
    free(pbuf);
  }
#endif
  for (int64_t i = 0; i < n_leaves; i++) {
    accumulate_update_to_range(vout + off[i], a + off[i], u + off[i], ns[i],
                               padded[i], 0, padded[i],
                               out_amax ? &out_amax[i] : NULL,
                               out_amax ? &out_ss[i] : NULL,
                               out_amax ? &out_sabs[i] : NULL);
  }
}

/* Functional one-pass form: out = clip(a + sanitize(u)) on live lanes,
 * out = a on padding (so a raw update's padding garbage never enters the
 * buffer — the caller no longer pre-masks or copies). Replaces the
 * copy-then-inplace pattern, which cost an extra full memory pass per
 * target array (the add path runs once per link residual plus the replica). */
EXPORT void stc_accumulate_update_to(float *vout, const float *a,
                                     const float *u, const int64_t *off,
                                     const int64_t *ns, const int64_t *padded,
                                     int64_t n_leaves) {
  accumulate_update_to_impl(vout, a, u, off, ns, padded, n_leaves, NULL, NULL,
                            NULL);
}

/* stc_accumulate_update_to + scale partials of the result in the same pass
 * (the stengine.cpp per-link partials cache: an add() that already walks a
 * residual refreshes its scale partials for free, killing the sender's
 * standalone stc_scale_partials scan — at 16 Mi that scan was a full 64 MiB
 * read per frame, 1/3 of the sender's memory traffic). */
EXPORT void stc_accumulate_update_to_partials(
    float *vout, const float *a, const float *u, const int64_t *off,
    const int64_t *ns, const int64_t *padded, int64_t n_leaves,
    double *out_amax, double *out_ss, double *out_sabs) {
  accumulate_update_to_impl(vout, a, u, off, ns, padded, n_leaves, out_amax,
                            out_ss, out_sabs);
}

/* ---- k-frame fused apply --------------------------------------------------
 *
 * out = clip(in + sum_f s_f*(1-2*bit_f)) in ONE pass over the target.
 * The batched receive path previously accumulated k frames into a delta
 * buffer (k read-modify-write passes over total*4 bytes) and then added the
 * delta to each target — at 16 Mi that is k*128 MiB of traffic before any
 * target is touched. This kernel reads each frame's PACKED words instead
 * (k * total/8 bytes — 16x smaller) and visits the target once:
 * per batch per target, 128 MiB + k*8 MiB instead of k*128 + 192 MiB.
 *
 * Bit-exact equivalence with both existing paths by construction:
 *   - the +/-s_f sum accumulates from 0 in frame order, exactly the order
 *     stc_accumulate_delta applied them to the delta buffer, and the final
 *     add+clip matches stc_add_to's clip(a + delta);
 *   - k == 1 reduces to clip(in +/- s), stc_apply_frame's arithmetic.
 * Leaves where every frame's scale is zero are copied verbatim (the k == 1
 * path's idle-leaf memcpy).
 *
 * EXCEPTION — the malloc-failure fallback below is NOT bit-identical for
 * k > 1: when the active-frame table cannot be allocated it applies frames
 * one at a time via stc_apply_frame, which clamps after EVERY frame and
 * rounds (in+d1)+d2 instead of in+(d1+...+dk) — up to ~1 ulp per element
 * off the fused path (more if intermediate sums hit the +/-3e38 clamp).
 * Rerouting through the provably-identical accumulate_delta+add_to
 * pipeline is not possible there: it needs a total*4-byte delta buffer,
 * and this branch exists precisely because allocation just failed. The
 * divergence only occurs under OOM and stays inside the ~1-ulp tier
 * tolerance every consumer of these arrays already accepts
 * (ADVICE r05 finding 4).
 *
 * Optional out_amax/out_ss/out_sabs (NULL ok): scale partials of the result,
 * fused like stc_quantize_ef_partials — for residual targets whose next
 * quantize needs them (stengine.cpp partials cache). */

#ifdef ST_AVX512
/* whole live words [w0, wl): m active (nonzero-scale) frames, per-frame
 * splatted scale vectors prebuilt by the caller. */
ST_TARGET_AVX512
static int64_t apply_frames_avx512(const float *in, float *out,
                                   const uint32_t *const *wps,
                                   const float *svals, int m, int64_t wl,
                                   int64_t w0, int do_part, double *amax,
                                   double *ss, double *sabs) {
  const __m512i vsign = _mm512_set1_epi32((int32_t)0x80000000u);
  const __m512 vmax = _mm512_set1_ps(3.0e38f);
  const __m512 vmin = _mm512_set1_ps(-3.0e38f);
  const __m512i vabsmask = _mm512_set1_epi32(0x7FFFFFFF);
  __m512 vamax = _mm512_setzero_ps();
  __m512d vss0 = _mm512_setzero_pd(), vss1 = _mm512_setzero_pd();
  __m512d vsa0 = _mm512_setzero_pd(), vsa1 = _mm512_setzero_pd();
  int64_t w = w0;
  for (; w < wl; w++) {
    __m512 acc0 = _mm512_setzero_ps();
    __m512 acc1 = _mm512_setzero_ps();
    for (int f = 0; f < m; f++) {
      uint32_t bits = wps[f][w];
      const __m512i vs = _mm512_castps_si512(_mm512_set1_ps(svals[f]));
      __mmask16 m0 = (__mmask16)bits;
      __mmask16 m1 = (__mmask16)(bits >> 16);
      acc0 = _mm512_add_ps(
          acc0, _mm512_castsi512_ps(_mm512_mask_xor_epi32(vs, m0, vs, vsign)));
      acc1 = _mm512_add_ps(
          acc1, _mm512_castsi512_ps(_mm512_mask_xor_epi32(vs, m1, vs, vsign)));
    }
    const float *pp = in + w * 32;
    float *qq = out + w * 32;
    __m512 r0 = _mm512_add_ps(_mm512_loadu_ps(pp), acc0);
    __m512 r1 = _mm512_add_ps(_mm512_loadu_ps(pp + 16), acc1);
    r0 = _mm512_max_ps(_mm512_min_ps(r0, vmax), vmin);
    r1 = _mm512_max_ps(_mm512_min_ps(r1, vmax), vmin);
    _mm512_storeu_ps(qq, r0);
    _mm512_storeu_ps(qq + 16, r1);
    if (do_part) {
      __m512 a0 = _mm512_castsi512_ps(
          _mm512_and_epi32(_mm512_castps_si512(r0), vabsmask));
      __m512 a1 = _mm512_castsi512_ps(
          _mm512_and_epi32(_mm512_castps_si512(r1), vabsmask));
      vamax = _mm512_max_ps(vamax, _mm512_max_ps(a0, a1));
      __m512d lo0 = _mm512_cvtps_pd(_mm512_castps512_ps256(r0));
      __m512d hi0 = _mm512_cvtps_pd(_mm512_extractf32x8_ps(r0, 1));
      __m512d lo1 = _mm512_cvtps_pd(_mm512_castps512_ps256(r1));
      __m512d hi1 = _mm512_cvtps_pd(_mm512_extractf32x8_ps(r1, 1));
      vss0 = _mm512_fmadd_pd(lo0, lo0, vss0);
      vss1 = _mm512_fmadd_pd(hi0, hi0, vss1);
      vss0 = _mm512_fmadd_pd(lo1, lo1, vss0);
      vss1 = _mm512_fmadd_pd(hi1, hi1, vss1);
      vsa0 = _mm512_add_pd(vsa0, _mm512_cvtps_pd(_mm512_castps512_ps256(a0)));
      vsa1 =
          _mm512_add_pd(vsa1, _mm512_cvtps_pd(_mm512_extractf32x8_ps(a0, 1)));
      vsa0 = _mm512_add_pd(vsa0, _mm512_cvtps_pd(_mm512_castps512_ps256(a1)));
      vsa1 =
          _mm512_add_pd(vsa1, _mm512_cvtps_pd(_mm512_extractf32x8_ps(a1, 1)));
    }
  }
  if (do_part) {
    *amax = _mm512_reduce_max_ps(vamax);
    *ss = _mm512_reduce_add_pd(vss0) + _mm512_reduce_add_pd(vss1);
    *sabs = _mm512_reduce_add_pd(vsa0) + _mm512_reduce_add_pd(vsa1);
  }
  return w;
}
#endif

/* One leaf's words [w0, w1): m active frames with word pointers wps[] and
 * scales svals[]. Partials (when requested) cover live lanes in range. */
ST_CLONES
static void apply_frames_range(const float *in, float *out,
                               const uint32_t *const *wps, const float *svals,
                               int m, int64_t n, int64_t pad, int64_t w0,
                               int64_t w1, double *out_amax, double *out_ss,
                               double *out_sabs) {
  double amax = 0, ssum = 0, sabs = 0;
  int64_t full = n / 32;
  if (full > w1) full = w1;
  int64_t k = w0;
  int do_part = out_amax != NULL;
#ifdef ST_AVX512
  if (st_has_avx512() && k < full) {
    double a2 = 0, s2 = 0, b2 = 0;
    k = apply_frames_avx512(in, out, wps, svals, m, full, w0, do_part, &a2,
                            &s2, &b2);
    if (do_part) {
      amax = a2;
      ssum = s2;
      sabs = b2;
    }
  }
#endif
  for (; k < full; k++) {
    for (int b = 0; b < 32; b++) {
      float acc = 0.0f;
      for (int f = 0; f < m; f++) {
        float s = svals[f];
        acc += ((wps[f][k] >> b) & 1u) ? -s : s;
      }
      float v = in[k * 32 + b] + acc;
      v = v > 3.0e38f ? 3.0e38f : v;
      v = v < -3.0e38f ? -3.0e38f : v;
      out[k * 32 + b] = v;
      if (do_part) {
        double a = v < 0 ? -(double)v : (double)v;
        if (a > amax) amax = a;
        ssum += (double)v * (double)v;
        sabs += a;
      }
    }
  }
  int64_t base = full * 32;
  if (n % 32 && n / 32 >= w0 && n / 32 < w1) {
    base = (n / 32) * 32;
    int64_t pw = n / 32;
    for (int64_t b = 0; b < n - base; b++) {
      float acc = 0.0f;
      for (int f = 0; f < m; f++) {
        float s = svals[f];
        acc += ((wps[f][pw] >> b) & 1u) ? -s : s;
      }
      float v = in[base + b] + acc;
      v = v > 3.0e38f ? 3.0e38f : v;
      v = v < -3.0e38f ? -3.0e38f : v;
      out[base + b] = v;
      if (do_part) {
        double a = v < 0 ? -(double)v : (double)v;
        if (a > amax) amax = a;
        ssum += (double)v * (double)v;
        sabs += a;
      }
    }
    for (int64_t b = n - base; b < 32 && base + b < pad; b++)
      out[base + b] = in[base + b];
    base += 32;
  }
  if (base < w0 * 32) base = w0 * 32;
  int64_t end = w1 * 32;
  if (base < end && base < pad) {
    int64_t stop = end < pad ? end : pad;
    if (stop > base)
      memcpy(out + base, in + base, (size_t)(stop - base) * sizeof(float));
  }
  if (out_amax) {
    *out_amax = amax;
    *out_ss = ssum;
    *out_sabs = sabs;
  }
}

/* idle-leaf range: copy + optional partials of the (unchanged) live lanes */
ST_CLONES
static void copy_partials_range(const float *in, float *out, int64_t n,
                                int64_t pad, int64_t e0, int64_t e1,
                                double *out_amax, double *out_ss,
                                double *out_sabs) {
  if (out != in) {
    int64_t stop = e1 < pad ? e1 : pad;
    if (stop > e0)
      memcpy(out + e0, in + e0, (size_t)(stop - e0) * sizeof(float));
  }
  if (out_amax) {
    int64_t live = n < e1 ? n : e1;
    scale_partials_range(out, e0 < live ? e0 : live, live, out_amax, out_ss,
                         out_sabs);
  }
}

typedef struct {
  const float *vin;
  float *vout;
  const int64_t *off, *ns, *padded;
  int64_t W;
  int32_t k;
  const float *scales;
  const uint32_t *words;
  double *camax, *css, *csabs;
#ifdef ST_POOL
  const stc_chunk *chunks;
#endif
  /* per-leaf active-frame table, built once by the wrapper: for leaf i,
   * frames af[i*k .. i*k+am[i]) are the nonzero-scale ones */
  const uint32_t *const *wps; /* [L * k] word pointers */
  const float *svals;         /* [L * k] scales */
  const int32_t *am;          /* [L] active counts */
} af_ctx;

static void apply_frames_leaf_range(af_ctx *x, int64_t i, int64_t w0,
                                    int64_t w1, double *pa, double *ps,
                                    double *pb) {
  int m = x->am[i];
  if (m == 0) {
    copy_partials_range(x->vin + x->off[i], x->vout + x->off[i], x->ns[i],
                        x->padded[i], w0 * 32, w1 * 32, pa, ps, pb);
    return;
  }
  apply_frames_range(x->vin + x->off[i], x->vout + x->off[i],
                     x->wps + (size_t)i * x->k, x->svals + (size_t)i * x->k, m,
                     x->ns[i], x->padded[i], w0, w1, pa, ps, pb);
}

#ifdef ST_POOL
static void apply_frames_seg(void *vctx, int64_t c) {
  af_ctx *x = (af_ctx *)vctx;
  const stc_chunk *ch = &x->chunks[c];
  apply_frames_leaf_range(x, ch->leaf, ch->w0, ch->w1,
                          x->camax ? &x->camax[c] : NULL,
                          x->camax ? &x->css[c] : NULL,
                          x->camax ? &x->csabs[c] : NULL);
}
#endif

/* shared tail of the fused-apply wrappers (flat and wire layouts build
 * the same per-leaf pointer tables, then run identically) */
static void apply_frames_run(af_ctx *x, int64_t n_leaves,
                             const int64_t *padded, double *out_amax,
                             double *out_ss, double *out_sabs) {
#ifdef ST_POOL
  int64_t total = 0;
  int64_t nc = stc_count_chunks(padded, n_leaves, &total);
  if (total >= ST_PAR_MIN_ELEMS) {
    stc_chunk *chunks = (stc_chunk *)malloc((size_t)nc * sizeof(stc_chunk));
    double *pbuf =
        out_amax ? (double *)malloc((size_t)nc * 3 * sizeof(double)) : NULL;
    if (chunks && (!out_amax || pbuf)) {
      stc_build_chunks(padded, n_leaves, chunks);
      x->chunks = chunks;
      x->camax = pbuf;
      x->css = pbuf ? pbuf + nc : NULL;
      x->csabs = pbuf ? pbuf + 2 * nc : NULL;
      if (stc_pool_run(apply_frames_seg, x, nc)) {
        if (out_amax)
          reduce_chunk_partials(chunks, nc, n_leaves, x->camax, x->css,
                                x->csabs, out_amax, out_ss, out_sabs);
        free(chunks);
        free(pbuf);
        return;
      }
      x->camax = NULL;
      x->css = NULL;
      x->csabs = NULL;
    }
    free(chunks);
    free(pbuf);
  }
#endif
  for (int64_t i = 0; i < n_leaves; i++) {
    apply_frames_leaf_range(x, i, 0, padded[i] / 32,
                            out_amax ? &out_amax[i] : NULL,
                            out_amax ? &out_ss[i] : NULL,
                            out_amax ? &out_sabs[i] : NULL);
  }
}

EXPORT void stc_apply_frames(const float *vin, float *vout, const int64_t *off,
                             const int64_t *ns, const int64_t *padded,
                             int64_t n_leaves, int64_t W, int32_t k,
                             const float *scales /* k*L */,
                             const uint32_t *words /* k*W */,
                             double *out_amax, double *out_ss,
                             double *out_sabs) {
  if (k <= 0) return;
  /* active-frame table: per leaf, the frames whose scale is nonzero */
  const uint32_t **wps =
      (const uint32_t **)malloc((size_t)n_leaves * k * sizeof(uint32_t *));
  float *svals = (float *)malloc((size_t)n_leaves * k * sizeof(float));
  int32_t *am = (int32_t *)malloc((size_t)n_leaves * sizeof(int32_t));
  if (!wps || !svals || !am) {
    /* OOM: frame-at-a-time fallback — ~1 ulp off the fused path for k > 1
     * (per-frame clamp + rounding; see the kernel header's EXCEPTION) */
    free(wps);
    free(svals);
    free(am);
    for (int32_t f = 0; f < k; f++)
      stc_apply_frame(f == 0 ? vin : vout, vout, off, ns, padded, n_leaves,
                      scales + (size_t)f * n_leaves, words + (size_t)f * W);
    if (out_amax)
      stc_scale_partials(vout, off, ns, n_leaves, out_amax, out_ss, out_sabs);
    return;
  }
  for (int64_t i = 0; i < n_leaves; i++) {
    int32_t m = 0;
    for (int32_t f = 0; f < k; f++) {
      float s = scales[(size_t)f * n_leaves + i];
      if (s == 0.0f) continue;
      wps[(size_t)i * k + m] = words + (size_t)f * W + off[i] / 32;
      svals[(size_t)i * k + m] = s;
      m++;
    }
    am[i] = m;
  }
  af_ctx x;
  x.vin = vin;
  x.vout = vout;
  x.off = off;
  x.ns = ns;
  x.padded = padded;
  x.W = W;
  x.k = k;
  x.scales = scales;
  x.words = words;
  x.camax = NULL;
  x.css = NULL;
  x.csabs = NULL;
  x.wps = wps;
  x.svals = svals;
  x.am = am;
  apply_frames_run(&x, n_leaves, padded, out_amax, out_ss, out_sabs);
  free(wps);
  free(svals);
  free(am);
}

/* r14: fused k-frame apply STRAIGHT FROM THE WIRE BODY — per frame f the
 * layout is [scales L*4][words W*4] at body + f*stride (the v3 aligned
 * framing guarantees body and stride are 4-aligned, so the typed loads
 * are legal). Identical arithmetic to stc_apply_frames: the workers only
 * ever see the per-leaf pointer table, which here points into the wire
 * buffer instead of a repacked copy — the receive path's full-message
 * repack (one read + one write of every wire byte) disappears. */
EXPORT void stc_apply_frames_wire(const float *vin, float *vout,
                                  const int64_t *off, const int64_t *ns,
                                  const int64_t *padded, int64_t n_leaves,
                                  int64_t W, int32_t k, const uint8_t *body,
                                  int64_t stride, double *out_amax,
                                  double *out_ss, double *out_sabs) {
  if (k <= 0) return;
  const uint32_t **wps =
      (const uint32_t **)malloc((size_t)n_leaves * k * sizeof(uint32_t *));
  float *svals = (float *)malloc((size_t)n_leaves * k * sizeof(float));
  int32_t *am = (int32_t *)malloc((size_t)n_leaves * sizeof(int32_t));
  if (!wps || !svals || !am) {
    free(wps);
    free(svals);
    free(am);
    for (int32_t f = 0; f < k; f++) {
      const uint8_t *fb = body + (size_t)f * stride;
      stc_apply_frame(f == 0 ? vin : vout, vout, off, ns, padded, n_leaves,
                      (const float *)fb,
                      (const uint32_t *)(fb + 4 * n_leaves));
    }
    if (out_amax)
      stc_scale_partials(vout, off, ns, n_leaves, out_amax, out_ss, out_sabs);
    return;
  }
  for (int64_t i = 0; i < n_leaves; i++) {
    int32_t m = 0;
    for (int32_t f = 0; f < k; f++) {
      const uint8_t *fb = body + (size_t)f * stride;
      float s = ((const float *)fb)[i];
      if (s == 0.0f) continue;
      wps[(size_t)i * k + m] =
          (const uint32_t *)(fb + 4 * n_leaves) + off[i] / 32;
      svals[(size_t)i * k + m] = s;
      m++;
    }
    am[i] = m;
  }
  af_ctx x;
  x.vin = vin;
  x.vout = vout;
  x.off = off;
  x.ns = ns;
  x.padded = padded;
  x.W = W;
  x.k = k;
  x.scales = NULL; /* workers read only the pointer tables */
  x.words = NULL;
  x.camax = NULL;
  x.css = NULL;
  x.csabs = NULL;
  x.wps = wps;
  x.svals = svals;
  x.am = am;
  apply_frames_run(&x, n_leaves, padded, out_amax, out_ss, out_sabs);
  free(wps);
  free(svals);
  free(am);
}

/* ======================================================================
 * r11: cascade quantize + sign2 (2-bit) kernels — the data-plane codecs
 * behind the next-10x arc (ROADMAP item 4).
 *
 * CASCADE QUANTIZE. The r07 burst sender quantizes K successive frames
 * of one residual as K full memory passes (each stc_quantize_ef_partials
 * call re-reads and re-writes the whole table), because frame k+1's scale
 * is re-measured from frame k's output. Measured on this box at 1 Mi that
 * pass is ~150 us and the pool's intra-pass parallelism has already
 * flattened — the PASS COUNT is the wall, not the bandwidth (the box
 * streams ~600 GB/s; the sender chain uses ~70). But scales are
 * SENDER-CHOSEN and ride the wire (receivers never recompute them), so a
 * sender may legally emit a frame schedule it predicts instead of
 * measures: successive halvings s, s/2, s/4, ... — which is exactly what
 * the measured schedule converges to anyway (pow2-RMS decays ~0.85/frame
 * => the pow2 floor halves every few frames), taken one frame earlier.
 * These kernels quantize K such frames in ONE pass, carrying the element
 * in registers across the K subtractions and emitting K bit planes: K
 * frames for one table read + one write + K/32 words. The wire format is
 * UNCHANGED — a cascade message is indistinguishable from K re-measured
 * frames, and the fused receive (stc_apply_frames) already applies K
 * frames in one pass. After a cascade the residual magnitude is bounded
 * by ~s/2^(K-1) (each level halves the bound), so per-message drain is
 * deeper than the measured schedule's, at identical bytes per frame.
 *
 * SIGN2 (2-bit sign/magnitude). The codec-lab winner (ops/codec_lab.py
 * Sign2, parallel/ici_lab.py build_sign2_sync_step) promoted to the
 * engine tier: sign bit + magnitude bit selecting +/-s or +/-3s
 * (magnitude set when |r| > 2s), zero-negative sign convention kept
 * (quirk Q3). Both magnitudes are exact f32 multiples of a pow2 scale
 * (3s has a 1.5 mantissa) so the 1-ulp conservation bound carries over.
 * Wire layout per frame: [scales L*4][sign words W*4][mag words W*4] —
 * two packed planes, the lab's exact layout. On a uniform residual the
 * magnitude bit idles and the trajectory is bit-identical to sign1; on
 * gaussian/outlier-heavy residuals (retransmit rollbacks, chaos) the
 * +/-3s level drains the tail 3x faster per frame — which is what the
 * engine's telemetry governor upshifts for (stengine.cpp).
 * ==================================================================== */

/* K halving levels for words [w0, w1) of one leaf. scales[j] is frame j's
 * scale for THIS leaf (any schedule; s == 0 levels record sign bits and
 * leave the residual untouched, stc_quantize's idle-leaf semantics).
 * Frame j's plane for this leaf lands at wp + j*wstride (wp already
 * offset to the leaf). Partials are of the FINAL residual. */
ST_CLONES
static void quantize_cascade_range(const float *p, float *q, int64_t n,
                                   const float *scales, int32_t k,
                                   uint32_t *wp, int64_t wstride, int64_t w0,
                                   int64_t w1, double *out_amax,
                                   double *out_ss, double *out_sabs) {
  double amax = 0, ssum = 0, sabs = 0;
  for (int64_t w = w0; w < w1; w++) {
    int64_t base = w * 32;
    int64_t lim = n - base;
    if (lim > 32) lim = 32;
    if (lim < 0) lim = 0;
    float buf[32];
    for (int64_t b = 0; b < lim; b++) buf[b] = p[base + b];
    for (int32_t j = 0; j < k; j++) {
      uint32_t bits = 0;
      float s = scales[j];
      if (s > 0.0f) {
        for (int64_t b = 0; b < lim; b++) {
          float v = buf[b];
          uint32_t neg = v <= 0.0f;
          bits |= neg << b;
          buf[b] = v - (neg ? -s : s);
        }
      } else {
        for (int64_t b = 0; b < lim; b++)
          bits |= (uint32_t)(buf[b] <= 0.0f) << b;
      }
      wp[(size_t)j * wstride + w] = bits;
    }
    for (int64_t b = 0; b < lim; b++) {
      float r = buf[b];
      q[base + b] = r;
      double a = r < 0 ? -(double)r : (double)r;
      if (a > amax) amax = a;
      ssum += (double)r * (double)r;
      sabs += a;
    }
    for (int64_t b = lim; b < 32; b++) q[base + b] = 0.0f;
  }
  *out_amax = amax;
  *out_ss = ssum;
  *out_sabs = sabs;
}

#ifdef ST_AVX512
/* Full-word AVX-512 body of the cascade: two 16-lane vectors stay in
 * registers across all K levels; partials of the final residual fused
 * (quantize_partials_leaf_avx512's arithmetic). Covers words
 * [w0, min(w1, n/32)); returns the stopping word. */
ST_TARGET_AVX512
static int64_t quantize_cascade_leaf_avx512(const float *p, float *q,
                                            int64_t n, const float *scales,
                                            int32_t k, uint32_t *wp,
                                            int64_t wstride, int64_t w0,
                                            int64_t w1, double *amax,
                                            double *ss, double *sabs) {
  const __m512 vzero = _mm512_setzero_ps();
  const __m512i vsign = _mm512_set1_epi32((int32_t)0x80000000u);
  const __m512i vabsmask = _mm512_set1_epi32(0x7FFFFFFF);
  __m512 vamax = _mm512_setzero_ps();
  __m512d vss0 = _mm512_setzero_pd(), vss1 = _mm512_setzero_pd();
  __m512d vsa0 = _mm512_setzero_pd(), vsa1 = _mm512_setzero_pd();
  int64_t w = w0, wl = n / 32 < w1 ? n / 32 : w1;
  for (; w < wl; w++) {
    __m512 v0 = _mm512_loadu_ps(p + w * 32);
    __m512 v1 = _mm512_loadu_ps(p + w * 32 + 16);
    for (int32_t j = 0; j < k; j++) {
      __mmask16 m0 = _mm512_cmp_ps_mask(v0, vzero, _CMP_LE_OQ);
      __mmask16 m1 = _mm512_cmp_ps_mask(v1, vzero, _CMP_LE_OQ);
      float s = scales[j];
      if (s > 0.0f) {
        const __m512i vs = _mm512_castps_si512(_mm512_set1_ps(s));
        __m512 d0 =
            _mm512_castsi512_ps(_mm512_mask_xor_epi32(vs, m0, vs, vsign));
        __m512 d1 =
            _mm512_castsi512_ps(_mm512_mask_xor_epi32(vs, m1, vs, vsign));
        v0 = _mm512_sub_ps(v0, d0);
        v1 = _mm512_sub_ps(v1, d1);
      }
      wp[(size_t)j * wstride + w] = (uint32_t)m0 | ((uint32_t)m1 << 16);
    }
    _mm512_storeu_ps(q + w * 32, v0);
    _mm512_storeu_ps(q + w * 32 + 16, v1);
    __m512 a0 = _mm512_castsi512_ps(
        _mm512_and_epi32(_mm512_castps_si512(v0), vabsmask));
    __m512 a1 = _mm512_castsi512_ps(
        _mm512_and_epi32(_mm512_castps_si512(v1), vabsmask));
    vamax = _mm512_max_ps(vamax, _mm512_max_ps(a0, a1));
    __m512d lo0 = _mm512_cvtps_pd(_mm512_castps512_ps256(v0));
    __m512d hi0 = _mm512_cvtps_pd(_mm512_extractf32x8_ps(v0, 1));
    __m512d lo1 = _mm512_cvtps_pd(_mm512_castps512_ps256(v1));
    __m512d hi1 = _mm512_cvtps_pd(_mm512_extractf32x8_ps(v1, 1));
    vss0 = _mm512_fmadd_pd(lo0, lo0, vss0);
    vss1 = _mm512_fmadd_pd(hi0, hi0, vss1);
    vss0 = _mm512_fmadd_pd(lo1, lo1, vss0);
    vss1 = _mm512_fmadd_pd(hi1, hi1, vss1);
    vsa0 = _mm512_add_pd(vsa0, _mm512_cvtps_pd(_mm512_castps512_ps256(a0)));
    vsa1 = _mm512_add_pd(vsa1, _mm512_cvtps_pd(_mm512_extractf32x8_ps(a0, 1)));
    vsa0 = _mm512_add_pd(vsa0, _mm512_cvtps_pd(_mm512_castps512_ps256(a1)));
    vsa1 = _mm512_add_pd(vsa1, _mm512_cvtps_pd(_mm512_extractf32x8_ps(a1, 1)));
  }
  *amax = _mm512_reduce_max_ps(vamax);
  *ss = _mm512_reduce_add_pd(vss0) + _mm512_reduce_add_pd(vss1);
  *sabs = _mm512_reduce_add_pd(vsa0) + _mm512_reduce_add_pd(vsa1);
  return w;
}
#endif

/* Range body with runtime AVX-512 dispatch (full words vectorized, the
 * live-tail word + partial-word handling stays scalar). */
static void quantize_cascade_dispatch(const float *p, float *q, int64_t n,
                                      const float *scales, int32_t k,
                                      uint32_t *wp, int64_t wstride,
                                      int64_t w0, int64_t w1, double *oa,
                                      double *os, double *ob) {
  int64_t w = w0;
  double a2 = 0, s2 = 0, b2 = 0;
#ifdef ST_AVX512
  if (st_has_avx512() && w < w1 && n / 32 > w0) {
    w = quantize_cascade_leaf_avx512(p, q, n, scales, k, wp, wstride, w0, w1,
                                     &a2, &s2, &b2);
  }
#endif
  double a3 = 0, s3 = 0, b3 = 0;
  if (w < w1)
    quantize_cascade_range(p, q, n, scales, k, wp, wstride, w, w1, &a3, &s3,
                           &b3);
  *oa = a2 > a3 ? a2 : a3;
  *os = s2 + s3;
  *ob = b2 + b3;
}

#ifdef ST_POOL
typedef struct {
  const float *rin;
  float *rout;
  const int64_t *off, *ns;
  const float *scales; /* k * L */
  int64_t n_leaves;
  int32_t k;
  uint32_t *words;
  int64_t wstride;
  const stc_chunk *chunks;
  double *camax, *css, *csabs;
} qzc_ctx;

static void quantize_cascade_seg(void *vctx, int64_t c) {
  qzc_ctx *x = (qzc_ctx *)vctx;
  const stc_chunk *ch = &x->chunks[c];
  int64_t i = ch->leaf;
  /* per-leaf schedule column: frame j's scale for leaf i */
  float ls[64];
  for (int32_t j = 0; j < x->k; j++)
    ls[j] = x->scales[(size_t)j * x->n_leaves + i];
  quantize_cascade_dispatch(x->rin + x->off[i], x->rout + x->off[i],
                            x->ns[i], ls, x->k, x->words + x->off[i] / 32,
                            x->wstride, ch->w0, ch->w1, &x->camax[c],
                            &x->css[c], &x->csabs[c]);
}
#endif

/* K frames in ONE pass over the residual. scales is k*L (frame-major, the
 * schedule the caller chose — stengine.cpp halves frame 0's measured
 * scales); frame j's bit plane lands at words + j*wstride (wstride in u32
 * words — the engine passes its wire-frame stride so planes land at their
 * final slot offsets). Partials (per leaf, of the final residual) feed the
 * next message's frame-0 scales exactly like stc_quantize_ef_partials.
 * k is capped at 64 (the engine never asks for more — a cascade below
 * s/2^63 is denormal territory long before). */
EXPORT void stc_quantize_ef_cascade(
    const float *rin, float *rout, const int64_t *off, const int64_t *ns,
    const int64_t *padded, int64_t n_leaves, int32_t k, const float *scales,
    uint32_t *words, int64_t wstride, double *out_amax, double *out_ss,
    double *out_sabs) {
  if (k < 1) k = 1;
  if (k > 64) k = 64;
#ifdef ST_POOL
  int64_t total = 0;
  int64_t nc = stc_count_chunks(padded, n_leaves, &total);
  if (total >= ST_PAR_MIN_ELEMS) {
    stc_chunk *chunks = (stc_chunk *)malloc((size_t)nc * sizeof(stc_chunk));
    double *pbuf = (double *)malloc((size_t)nc * 3 * sizeof(double));
    if (chunks && pbuf) {
      stc_build_chunks(padded, n_leaves, chunks);
      qzc_ctx x = {rin,   rout,    off,  ns,        scales,
                   n_leaves, k,    words, wstride,  chunks,
                   pbuf,  pbuf + nc, pbuf + 2 * nc};
      if (stc_pool_run(quantize_cascade_seg, &x, nc)) {
        reduce_chunk_partials(chunks, nc, n_leaves, x.camax, x.css, x.csabs,
                              out_amax, out_ss, out_sabs);
        free(chunks);
        free(pbuf);
        return;
      }
    }
    free(chunks);
    free(pbuf);
  }
#endif
  for (int64_t i = 0; i < n_leaves; i++) {
    float ls[64];
    for (int32_t j = 0; j < k; j++)
      ls[j] = scales[(size_t)j * n_leaves + i];
    quantize_cascade_dispatch(rin + off[i], rout + off[i], ns[i], ls, k,
                              words + off[i] / 32, wstride, 0, padded[i] / 32,
                              &out_amax[i], &out_ss[i], &out_sabs[i]);
  }
}

/* sign2 cascade: K levels of the 2-bit rule in one pass. Frame j's sign
 * plane lands at wp + j*wstride, its magnitude plane W words after (the
 * wire layout: [scales][sign W][mag W] per frame). Level semantics match
 * the lab reference exactly: neg = r <= 0, big = |r| > 2s (with s == 0
 * that is |r| > 0 — bits still recorded, residual untouched, the
 * idle-leaf twin of the 1-bit kernels). */
ST_CLONES
static void quantize2_cascade_range(const float *p, float *q, int64_t n,
                                    const float *scales, int32_t k,
                                    uint32_t *wp, int64_t wstride, int64_t W,
                                    int64_t w0, int64_t w1, double *out_amax,
                                    double *out_ss, double *out_sabs) {
  double amax = 0, ssum = 0, sabs = 0;
  for (int64_t w = w0; w < w1; w++) {
    int64_t base = w * 32;
    int64_t lim = n - base;
    if (lim > 32) lim = 32;
    if (lim < 0) lim = 0;
    float buf[32];
    for (int64_t b = 0; b < lim; b++) buf[b] = p[base + b];
    for (int32_t j = 0; j < k; j++) {
      uint32_t sbits = 0, mbits = 0;
      float s = scales[j];
      float s2x = 2.0f * s, s3x = 3.0f * s;
      for (int64_t b = 0; b < lim; b++) {
        float v = buf[b];
        uint32_t neg = v <= 0.0f;
        float av = v < 0.0f ? -v : v;
        uint32_t big = av > s2x;
        sbits |= neg << b;
        mbits |= big << b;
        if (s > 0.0f) {
          float mag = big ? s3x : s;
          buf[b] = v - (neg ? -mag : mag);
        }
      }
      wp[(size_t)j * wstride + w] = sbits;
      wp[(size_t)j * wstride + W + w] = mbits;
    }
    for (int64_t b = 0; b < lim; b++) {
      float r = buf[b];
      q[base + b] = r;
      double a = r < 0 ? -(double)r : (double)r;
      if (a > amax) amax = a;
      ssum += (double)r * (double)r;
      sabs += a;
    }
    for (int64_t b = lim; b < 32; b++) q[base + b] = 0.0f;
  }
  *out_amax = amax;
  *out_ss = ssum;
  *out_sabs = sabs;
}

#ifdef ST_AVX512
/* Full-word AVX-512 body of the sign2 cascade (quantize_cascade_leaf_
 * avx512's 2-bit twin): the element rides registers across all K levels;
 * per level, two compare masks ARE the wire planes (neg -> sign bits,
 * |v| > 2s -> magnitude bits) and the subtrahend is the magnitude blend
 * (+/-s or +/-3s) sign-flipped by mask — bit- and ulp-identical to the
 * scalar rule (2.0f*s / 3.0f*s precomputed in f32 exactly like it).
 * Covers words [w0, min(w1, n/32)); returns the stopping word. */
ST_TARGET_AVX512
static int64_t quantize2_cascade_leaf_avx512(
    const float *p, float *q, int64_t n, const float *scales, int32_t k,
    uint32_t *wp, int64_t wstride, int64_t W, int64_t w0, int64_t w1,
    double *amax, double *ss, double *sabs) {
  const __m512 vzero = _mm512_setzero_ps();
  const __m512i vsign = _mm512_set1_epi32((int32_t)0x80000000u);
  const __m512i vabsmask = _mm512_set1_epi32(0x7FFFFFFF);
  __m512 vamax = _mm512_setzero_ps();
  __m512d vss0 = _mm512_setzero_pd(), vss1 = _mm512_setzero_pd();
  __m512d vsa0 = _mm512_setzero_pd(), vsa1 = _mm512_setzero_pd();
  int64_t w = w0, wl = n / 32 < w1 ? n / 32 : w1;
  for (; w < wl; w++) {
    __m512 v0 = _mm512_loadu_ps(p + w * 32);
    __m512 v1 = _mm512_loadu_ps(p + w * 32 + 16);
    for (int32_t j = 0; j < k; j++) {
      float s = scales[j];
      const __m512 vs2 = _mm512_set1_ps(2.0f * s);
      __mmask16 n0 = _mm512_cmp_ps_mask(v0, vzero, _CMP_LE_OQ);
      __mmask16 n1 = _mm512_cmp_ps_mask(v1, vzero, _CMP_LE_OQ);
      __m512 a0 = _mm512_castsi512_ps(
          _mm512_and_epi32(_mm512_castps_si512(v0), vabsmask));
      __m512 a1 = _mm512_castsi512_ps(
          _mm512_and_epi32(_mm512_castps_si512(v1), vabsmask));
      __mmask16 b0 = _mm512_cmp_ps_mask(a0, vs2, _CMP_GT_OQ);
      __mmask16 b1 = _mm512_cmp_ps_mask(a1, vs2, _CMP_GT_OQ);
      if (s > 0.0f) {
        const __m512 vs = _mm512_set1_ps(s);
        const __m512 vs3 = _mm512_set1_ps(3.0f * s);
        __m512i mag0 = _mm512_castps_si512(_mm512_mask_mov_ps(vs, b0, vs3));
        __m512i mag1 = _mm512_castps_si512(_mm512_mask_mov_ps(vs, b1, vs3));
        __m512 d0 =
            _mm512_castsi512_ps(_mm512_mask_xor_epi32(mag0, n0, mag0, vsign));
        __m512 d1 =
            _mm512_castsi512_ps(_mm512_mask_xor_epi32(mag1, n1, mag1, vsign));
        v0 = _mm512_sub_ps(v0, d0);
        v1 = _mm512_sub_ps(v1, d1);
      }
      wp[(size_t)j * wstride + w] = (uint32_t)n0 | ((uint32_t)n1 << 16);
      wp[(size_t)j * wstride + W + w] = (uint32_t)b0 | ((uint32_t)b1 << 16);
    }
    _mm512_storeu_ps(q + w * 32, v0);
    _mm512_storeu_ps(q + w * 32 + 16, v1);
    __m512 a0 = _mm512_castsi512_ps(
        _mm512_and_epi32(_mm512_castps_si512(v0), vabsmask));
    __m512 a1 = _mm512_castsi512_ps(
        _mm512_and_epi32(_mm512_castps_si512(v1), vabsmask));
    vamax = _mm512_max_ps(vamax, _mm512_max_ps(a0, a1));
    __m512d lo0 = _mm512_cvtps_pd(_mm512_castps512_ps256(v0));
    __m512d hi0 = _mm512_cvtps_pd(_mm512_extractf32x8_ps(v0, 1));
    __m512d lo1 = _mm512_cvtps_pd(_mm512_castps512_ps256(v1));
    __m512d hi1 = _mm512_cvtps_pd(_mm512_extractf32x8_ps(v1, 1));
    vss0 = _mm512_fmadd_pd(lo0, lo0, vss0);
    vss1 = _mm512_fmadd_pd(hi0, hi0, vss1);
    vss0 = _mm512_fmadd_pd(lo1, lo1, vss0);
    vss1 = _mm512_fmadd_pd(hi1, hi1, vss1);
    vsa0 = _mm512_add_pd(vsa0, _mm512_cvtps_pd(_mm512_castps512_ps256(a0)));
    vsa1 = _mm512_add_pd(vsa1, _mm512_cvtps_pd(_mm512_extractf32x8_ps(a0, 1)));
    vsa0 = _mm512_add_pd(vsa0, _mm512_cvtps_pd(_mm512_castps512_ps256(a1)));
    vsa1 = _mm512_add_pd(vsa1, _mm512_cvtps_pd(_mm512_extractf32x8_ps(a1, 1)));
  }
  *amax = _mm512_reduce_max_ps(vamax);
  *ss = _mm512_reduce_add_pd(vss0) + _mm512_reduce_add_pd(vss1);
  *sabs = _mm512_reduce_add_pd(vsa0) + _mm512_reduce_add_pd(vsa1);
  return w;
}
#endif

/* Range body with runtime AVX-512 dispatch (full words vectorized, the
 * live-tail word + partial-word handling stays scalar — same split as
 * quantize_cascade_dispatch). */
static void quantize2_cascade_dispatch(const float *p, float *q, int64_t n,
                                       const float *scales, int32_t k,
                                       uint32_t *wp, int64_t wstride,
                                       int64_t W, int64_t w0, int64_t w1,
                                       double *oa, double *os, double *ob) {
  int64_t w = w0;
  double a2 = 0, s2 = 0, b2 = 0;
#ifdef ST_AVX512
  if (st_has_avx512() && w < w1 && n / 32 > w0) {
    w = quantize2_cascade_leaf_avx512(p, q, n, scales, k, wp, wstride, W, w0,
                                      w1, &a2, &s2, &b2);
  }
#endif
  double a3 = 0, s3 = 0, b3 = 0;
  if (w < w1)
    quantize2_cascade_range(p, q, n, scales, k, wp, wstride, W, w, w1, &a3,
                            &s3, &b3);
  *oa = a2 > a3 ? a2 : a3;
  *os = s2 + s3;
  *ob = b2 + b3;
}

#ifdef ST_POOL
typedef struct {
  const float *rin;
  float *rout;
  const int64_t *off, *ns;
  const float *scales;
  int64_t n_leaves;
  int32_t k;
  uint32_t *words;
  int64_t wstride, W;
  const stc_chunk *chunks;
  double *camax, *css, *csabs;
} qz2_ctx;

static void quantize2_cascade_seg(void *vctx, int64_t c) {
  qz2_ctx *x = (qz2_ctx *)vctx;
  const stc_chunk *ch = &x->chunks[c];
  int64_t i = ch->leaf;
  float ls[64];
  for (int32_t j = 0; j < x->k; j++)
    ls[j] = x->scales[(size_t)j * x->n_leaves + i];
  quantize2_cascade_dispatch(x->rin + x->off[i], x->rout + x->off[i],
                             x->ns[i], ls, x->k, x->words + x->off[i] / 32,
                             x->wstride, x->W, ch->w0, ch->w1, &x->camax[c],
                             &x->css[c], &x->csabs[c]);
}
#endif

/* The sign2 sender kernel (k = 1 is the plain per-frame quantize the
 * parity tests pin against the JAX lab). words/wstride as in
 * stc_quantize_ef_cascade; W is the table's total word count (locates the
 * magnitude plane inside each frame). */
EXPORT void stc_quantize2_ef_cascade(
    const float *rin, float *rout, const int64_t *off, const int64_t *ns,
    const int64_t *padded, int64_t n_leaves, int32_t k, const float *scales,
    uint32_t *words, int64_t wstride, int64_t W, double *out_amax,
    double *out_ss, double *out_sabs) {
  if (k < 1) k = 1;
  if (k > 64) k = 64;
#ifdef ST_POOL
  int64_t total = 0;
  int64_t nc = stc_count_chunks(padded, n_leaves, &total);
  if (total >= ST_PAR_MIN_ELEMS) {
    stc_chunk *chunks = (stc_chunk *)malloc((size_t)nc * sizeof(stc_chunk));
    double *pbuf = (double *)malloc((size_t)nc * 3 * sizeof(double));
    if (chunks && pbuf) {
      stc_build_chunks(padded, n_leaves, chunks);
      qz2_ctx x = {rin,      rout, off,   ns,      scales, n_leaves, k,
                   words,    wstride, W,  chunks,  pbuf,   pbuf + nc,
                   pbuf + 2 * nc};
      if (stc_pool_run(quantize2_cascade_seg, &x, nc)) {
        reduce_chunk_partials(chunks, nc, n_leaves, x.camax, x.css, x.csabs,
                              out_amax, out_ss, out_sabs);
        free(chunks);
        free(pbuf);
        return;
      }
    }
    free(chunks);
    free(pbuf);
  }
#endif
  for (int64_t i = 0; i < n_leaves; i++) {
    float ls[64];
    for (int32_t j = 0; j < k; j++)
      ls[j] = scales[(size_t)j * n_leaves + i];
    quantize2_cascade_dispatch(rin + off[i], rout + off[i], ns[i], ls, k,
                               words + off[i] / 32, wstride, W, 0,
                               padded[i] / 32, &out_amax[i], &out_ss[i],
                               &out_sabs[i]);
  }
}

/* ---- sign2 receive: fused k-frame apply --------------------------------
 * delta = s * (sign ? -1 : +1) * (mag ? 3 : 1), summed across the active
 * frames, one pass per target, +/-3e38 clamp at the end — the sign2 twin
 * of apply_frames_range (same ~1-ulp note vs per-frame application). */

#ifdef ST_AVX512
/* whole live words [w0, wl): the per-frame subtrahend is the magnitude
 * blend (s or 3.0f*s by the mag plane) sign-flipped by the sign plane —
 * apply_frames_avx512 with one extra mask_mov per frame, ulp-identical
 * to the scalar accumulation order. */
ST_TARGET_AVX512
static int64_t apply2_frames_avx512(const float *in, float *out,
                                    const uint32_t *const *sps,
                                    const uint32_t *const *mps,
                                    const float *svals, int m, int64_t wl,
                                    int64_t w0, int do_part, double *amax,
                                    double *ss, double *sabs) {
  const __m512i vsign = _mm512_set1_epi32((int32_t)0x80000000u);
  const __m512 vmax = _mm512_set1_ps(3.0e38f);
  const __m512 vmin = _mm512_set1_ps(-3.0e38f);
  const __m512i vabsmask = _mm512_set1_epi32(0x7FFFFFFF);
  __m512 vamax = _mm512_setzero_ps();
  __m512d vss0 = _mm512_setzero_pd(), vss1 = _mm512_setzero_pd();
  __m512d vsa0 = _mm512_setzero_pd(), vsa1 = _mm512_setzero_pd();
  int64_t w = w0;
  for (; w < wl; w++) {
    __m512 acc0 = _mm512_setzero_ps();
    __m512 acc1 = _mm512_setzero_ps();
    for (int f = 0; f < m; f++) {
      uint32_t sb = sps[f][w], mb = mps[f][w];
      const __m512 vs = _mm512_set1_ps(svals[f]);
      const __m512 vs3 = _mm512_set1_ps(3.0f * svals[f]);
      __m512i mag0 = _mm512_castps_si512(
          _mm512_mask_mov_ps(vs, (__mmask16)mb, vs3));
      __m512i mag1 = _mm512_castps_si512(
          _mm512_mask_mov_ps(vs, (__mmask16)(mb >> 16), vs3));
      acc0 = _mm512_add_ps(
          acc0, _mm512_castsi512_ps(_mm512_mask_xor_epi32(
                    mag0, (__mmask16)sb, mag0, vsign)));
      acc1 = _mm512_add_ps(
          acc1, _mm512_castsi512_ps(_mm512_mask_xor_epi32(
                    mag1, (__mmask16)(sb >> 16), mag1, vsign)));
    }
    const float *pp = in + w * 32;
    float *qq = out + w * 32;
    __m512 r0 = _mm512_add_ps(_mm512_loadu_ps(pp), acc0);
    __m512 r1 = _mm512_add_ps(_mm512_loadu_ps(pp + 16), acc1);
    r0 = _mm512_max_ps(_mm512_min_ps(r0, vmax), vmin);
    r1 = _mm512_max_ps(_mm512_min_ps(r1, vmax), vmin);
    _mm512_storeu_ps(qq, r0);
    _mm512_storeu_ps(qq + 16, r1);
    if (do_part) {
      __m512 a0 = _mm512_castsi512_ps(
          _mm512_and_epi32(_mm512_castps_si512(r0), vabsmask));
      __m512 a1 = _mm512_castsi512_ps(
          _mm512_and_epi32(_mm512_castps_si512(r1), vabsmask));
      vamax = _mm512_max_ps(vamax, _mm512_max_ps(a0, a1));
      __m512d lo0 = _mm512_cvtps_pd(_mm512_castps512_ps256(r0));
      __m512d hi0 = _mm512_cvtps_pd(_mm512_extractf32x8_ps(r0, 1));
      __m512d lo1 = _mm512_cvtps_pd(_mm512_castps512_ps256(r1));
      __m512d hi1 = _mm512_cvtps_pd(_mm512_extractf32x8_ps(r1, 1));
      vss0 = _mm512_fmadd_pd(lo0, lo0, vss0);
      vss1 = _mm512_fmadd_pd(hi0, hi0, vss1);
      vss0 = _mm512_fmadd_pd(lo1, lo1, vss0);
      vss1 = _mm512_fmadd_pd(hi1, hi1, vss1);
      vsa0 = _mm512_add_pd(vsa0, _mm512_cvtps_pd(_mm512_castps512_ps256(a0)));
      vsa1 =
          _mm512_add_pd(vsa1, _mm512_cvtps_pd(_mm512_extractf32x8_ps(a0, 1)));
      vsa0 = _mm512_add_pd(vsa0, _mm512_cvtps_pd(_mm512_castps512_ps256(a1)));
      vsa1 =
          _mm512_add_pd(vsa1, _mm512_cvtps_pd(_mm512_extractf32x8_ps(a1, 1)));
    }
  }
  if (do_part) {
    *amax = _mm512_reduce_max_ps(vamax);
    *ss = _mm512_reduce_add_pd(vss0) + _mm512_reduce_add_pd(vss1);
    *sabs = _mm512_reduce_add_pd(vsa0) + _mm512_reduce_add_pd(vsa1);
  }
  return w;
}
#endif

ST_CLONES
static void apply2_frames_range(const float *in, float *out,
                                const uint32_t *const *sps,
                                const uint32_t *const *mps,
                                const float *svals, int m, int64_t n,
                                int64_t pad, int64_t w0, int64_t w1,
                                double *out_amax, double *out_ss,
                                double *out_sabs) {
  double amax = 0, ssum = 0, sabs = 0;
  int64_t full = n / 32;
  if (full > w1) full = w1;
  int do_part = out_amax != NULL;
  int64_t k = w0;
#ifdef ST_AVX512
  if (st_has_avx512() && k < full) {
    double a2 = 0, s2 = 0, b2 = 0;
    k = apply2_frames_avx512(in, out, sps, mps, svals, m, full, w0, do_part,
                             &a2, &s2, &b2);
    if (do_part) {
      amax = a2;
      ssum = s2;
      sabs = b2;
    }
  }
#endif
  for (; k < full; k++) {
    for (int b = 0; b < 32; b++) {
      float acc = 0.0f;
      for (int f = 0; f < m; f++) {
        float s = svals[f];
        float d = ((mps[f][k] >> b) & 1u) ? 3.0f * s : s;
        acc += ((sps[f][k] >> b) & 1u) ? -d : d;
      }
      float v = in[k * 32 + b] + acc;
      v = v > 3.0e38f ? 3.0e38f : v;
      v = v < -3.0e38f ? -3.0e38f : v;
      out[k * 32 + b] = v;
      if (do_part) {
        double a = v < 0 ? -(double)v : (double)v;
        if (a > amax) amax = a;
        ssum += (double)v * (double)v;
        sabs += a;
      }
    }
  }
  int64_t base = full * 32;
  if (n % 32 && n / 32 >= w0 && n / 32 < w1) {
    base = (n / 32) * 32;
    int64_t pw = n / 32;
    for (int64_t b = 0; b < n - base; b++) {
      float acc = 0.0f;
      for (int f = 0; f < m; f++) {
        float s = svals[f];
        float d = ((mps[f][pw] >> b) & 1u) ? 3.0f * s : s;
        acc += ((sps[f][pw] >> b) & 1u) ? -d : d;
      }
      float v = in[base + b] + acc;
      v = v > 3.0e38f ? 3.0e38f : v;
      v = v < -3.0e38f ? -3.0e38f : v;
      out[base + b] = v;
      if (do_part) {
        double a = v < 0 ? -(double)v : (double)v;
        if (a > amax) amax = a;
        ssum += (double)v * (double)v;
        sabs += a;
      }
    }
    for (int64_t b = n - base; b < 32 && base + b < pad; b++)
      out[base + b] = in[base + b];
    base += 32;
  }
  if (base < w0 * 32) base = w0 * 32;
  int64_t end = w1 * 32;
  if (base < end && base < pad) {
    int64_t stop = end < pad ? end : pad;
    if (stop > base)
      memcpy(out + base, in + base, (size_t)(stop - base) * sizeof(float));
  }
  if (out_amax) {
    *out_amax = amax;
    *out_ss = ssum;
    *out_sabs = sabs;
  }
}

typedef struct {
  const float *vin;
  float *vout;
  const int64_t *off, *ns, *padded;
  int64_t W;
  int32_t k;
  double *camax, *css, *csabs;
#ifdef ST_POOL
  const stc_chunk *chunks;
#endif
  const uint32_t *const *sps; /* [L * k] sign-plane pointers */
  const uint32_t *const *mps; /* [L * k] mag-plane pointers */
  const float *svals;         /* [L * k] scales */
  const int32_t *am;          /* [L] active counts */
} af2_ctx;

static void apply2_frames_leaf_range(af2_ctx *x, int64_t i, int64_t w0,
                                     int64_t w1, double *pa, double *ps,
                                     double *pb) {
  int m = x->am[i];
  if (m == 0) {
    copy_partials_range(x->vin + x->off[i], x->vout + x->off[i], x->ns[i],
                        x->padded[i], w0 * 32, w1 * 32, pa, ps, pb);
    return;
  }
  apply2_frames_range(x->vin + x->off[i], x->vout + x->off[i],
                      x->sps + (size_t)i * x->k, x->mps + (size_t)i * x->k,
                      x->svals + (size_t)i * x->k, m, x->ns[i], x->padded[i],
                      w0, w1, pa, ps, pb);
}

#ifdef ST_POOL
static void apply2_frames_seg(void *vctx, int64_t c) {
  af2_ctx *x = (af2_ctx *)vctx;
  const stc_chunk *ch = &x->chunks[c];
  apply2_frames_leaf_range(x, ch->leaf, ch->w0, ch->w1,
                           x->camax ? &x->camax[c] : NULL,
                           x->camax ? &x->css[c] : NULL,
                           x->camax ? &x->csabs[c] : NULL);
}
#endif

/* shared tail (see apply_frames_run) */
static void apply2_frames_run(af2_ctx *x, int64_t n_leaves,
                              const int64_t *padded, double *out_amax,
                              double *out_ss, double *out_sabs) {
#ifdef ST_POOL
  int64_t total = 0;
  int64_t nc = stc_count_chunks(padded, n_leaves, &total);
  if (total >= ST_PAR_MIN_ELEMS) {
    stc_chunk *chunks = (stc_chunk *)malloc((size_t)nc * sizeof(stc_chunk));
    double *pbuf =
        out_amax ? (double *)malloc((size_t)nc * 3 * sizeof(double)) : NULL;
    if (chunks && (!out_amax || pbuf)) {
      stc_build_chunks(padded, n_leaves, chunks);
      x->chunks = chunks;
      x->camax = pbuf;
      x->css = pbuf ? pbuf + nc : NULL;
      x->csabs = pbuf ? pbuf + 2 * nc : NULL;
      if (stc_pool_run(apply2_frames_seg, x, nc)) {
        if (out_amax)
          reduce_chunk_partials(chunks, nc, n_leaves, x->camax, x->css,
                                x->csabs, out_amax, out_ss, out_sabs);
        free(chunks);
        free(pbuf);
        return;
      }
      x->camax = NULL;
      x->css = NULL;
      x->csabs = NULL;
    }
    free(chunks);
    free(pbuf);
  }
#endif
  for (int64_t i = 0; i < n_leaves; i++) {
    apply2_frames_leaf_range(x, i, 0, padded[i] / 32,
                             out_amax ? &out_amax[i] : NULL,
                             out_amax ? &out_ss[i] : NULL,
                             out_amax ? &out_sabs[i] : NULL);
  }
}

/* Fused k-frame sign2 apply (stc_apply_frames's 2-bit twin). words is
 * k * 2W: frame f's sign plane at f*2W, its magnitude plane at f*2W + W —
 * exactly the order the planes arrive inside a wire frame body. */
EXPORT void stc_apply_frames2(const float *vin, float *vout,
                              const int64_t *off, const int64_t *ns,
                              const int64_t *padded, int64_t n_leaves,
                              int64_t W, int32_t k,
                              const float *scales /* k*L */,
                              const uint32_t *words /* k*2W */,
                              double *out_amax, double *out_ss,
                              double *out_sabs) {
  if (k <= 0) return;
  const uint32_t **sps =
      (const uint32_t **)malloc((size_t)n_leaves * k * 2 * sizeof(uint32_t *));
  float *svals = (float *)malloc((size_t)n_leaves * k * sizeof(float));
  int32_t *am = (int32_t *)malloc((size_t)n_leaves * sizeof(int32_t));
  if (!sps || !svals || !am) {
    free(sps);
    free(svals);
    free(am);
    return; /* OOM on tiny metadata arrays: nothing sane left to do */
  }
  const uint32_t **mps = sps + (size_t)n_leaves * k;
  for (int64_t i = 0; i < n_leaves; i++) {
    int32_t m = 0;
    for (int32_t f = 0; f < k; f++) {
      float s = scales[(size_t)f * n_leaves + i];
      if (s == 0.0f) continue;
      sps[(size_t)i * k + m] = words + (size_t)f * 2 * W + off[i] / 32;
      mps[(size_t)i * k + m] = words + (size_t)f * 2 * W + W + off[i] / 32;
      svals[(size_t)i * k + m] = s;
      m++;
    }
    am[i] = m;
  }
  af2_ctx x;
  x.vin = vin;
  x.vout = vout;
  x.off = off;
  x.ns = ns;
  x.padded = padded;
  x.W = W;
  x.k = k;
  x.camax = NULL;
  x.css = NULL;
  x.csabs = NULL;
  x.sps = sps;
  x.mps = mps;
  x.svals = svals;
  x.am = am;
  apply2_frames_run(&x, n_leaves, padded, out_amax, out_ss, out_sabs);
  free(sps);
  free(svals);
  free(am);
}

/* r14: the sign2 twin of stc_apply_frames_wire — per frame f the wire
 * body is [scales L*4][sign W*4][mag W*4] at body + f*stride (4-aligned
 * by the v3 framing). */
EXPORT void stc_apply_frames2_wire(const float *vin, float *vout,
                                   const int64_t *off, const int64_t *ns,
                                   const int64_t *padded, int64_t n_leaves,
                                   int64_t W, int32_t k, const uint8_t *body,
                                   int64_t stride, double *out_amax,
                                   double *out_ss, double *out_sabs) {
  if (k <= 0) return;
  const uint32_t **sps =
      (const uint32_t **)malloc((size_t)n_leaves * k * 2 * sizeof(uint32_t *));
  float *svals = (float *)malloc((size_t)n_leaves * k * sizeof(float));
  int32_t *am = (int32_t *)malloc((size_t)n_leaves * sizeof(int32_t));
  if (!sps || !svals || !am) {
    free(sps);
    free(svals);
    free(am);
    return; /* OOM on tiny metadata arrays: nothing sane left to do */
  }
  const uint32_t **mps = sps + (size_t)n_leaves * k;
  for (int64_t i = 0; i < n_leaves; i++) {
    int32_t m = 0;
    for (int32_t f = 0; f < k; f++) {
      const uint8_t *fb = body + (size_t)f * stride;
      float s = ((const float *)fb)[i];
      if (s == 0.0f) continue;
      const uint32_t *w = (const uint32_t *)(fb + 4 * n_leaves);
      sps[(size_t)i * k + m] = w + off[i] / 32;
      mps[(size_t)i * k + m] = w + W + off[i] / 32;
      svals[(size_t)i * k + m] = s;
      m++;
    }
    am[i] = m;
  }
  af2_ctx x;
  x.vin = vin;
  x.vout = vout;
  x.off = off;
  x.ns = ns;
  x.padded = padded;
  x.W = W;
  x.k = k;
  x.camax = NULL;
  x.css = NULL;
  x.csabs = NULL;
  x.sps = sps;
  x.mps = mps;
  x.svals = svals;
  x.am = am;
  apply2_frames_run(&x, n_leaves, padded, out_amax, out_ss, out_sabs);
  free(sps);
  free(svals);
  free(am);
}

/* Single sign2 frame applied in place (the engine's rollback path: re-
 * applying a ledgered sign2 frame to the residual restores the
 * pre-quantize state, the 1-bit _unapply discipline). words = [sign W |
 * mag W], the frame's wire body layout. */
EXPORT void stc_apply_frame2(const float *vin, float *vout,
                             const int64_t *off, const int64_t *ns,
                             const int64_t *padded, int64_t n_leaves,
                             int64_t W, const float *scales,
                             const uint32_t *words) {
  stc_apply_frames2(vin, vout, off, ns, padded, n_leaves, W, 1, scales, words,
                    NULL, NULL, NULL);
}
