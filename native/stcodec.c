/* stcodec: native host-tier codec hot loops.
 *
 * The reference's entire codec is ~30 lines of C inside its link threads
 * (reference src/sharedtensor.c:106-111 receiver, :153-174 sender), measured
 * at 202 M elem/s on one core (BASELINE.md) — the system's bottleneck. Our
 * host tier's numpy implementation (ops/codec_np.py) costs ~8 memory passes
 * per frame where the C loop needs ~2 fused ones; this library provides
 * those fused loops for CPU peers. The TPU tier is ops/codec_pallas.py; the
 * numpy tier remains the always-available fallback and the semantic
 * reference for these functions (bit-identical given the same scales).
 *
 * Table layout (ops/table.py): one flat f32 buffer; leaf i occupies
 * [off[i], off[i]+padded[i]) with ns[i] live elements at the front, padding
 * exactly 0. Bits are LSB-first: flat bit j -> word[j/32] bit j%32
 * (ops/packing.py wire contract; byte-identical to the reference's
 * data[i/8] |= 1 << (i%8)).
 *
 * Plain C ABI for ctypes (no pybind11 in this image). Single-threaded by
 * design: one link engine per thread, like the reference.
 */

#include <stdint.h>
#include <string.h>

#define EXPORT __attribute__((visibility("default")))

/* AVX-512 fast paths with RUNTIME dispatch. The reference's scalar loops run
 * ~200 M elem/s/core (BASELINE.md); the sign-quantize and apply loops below
 * are 1-bit-per-float mask ops, which AVX-512 expresses directly
 * (compare->__mmask16 is the codec's bitmask, bit-for-bit). Scalar code
 * stays as the portable fallback and the semantic reference.
 *
 * Why runtime and not -march=native: a prebuilt libstcodec.so can travel to
 * another machine (docker image, rsync'd checkout, NFS) where make's
 * mtime-only check sees it as fresh — compile-time-only AVX-512 would then
 * SIGILL the peer process on a non-AVX-512 host. The AVX-512 bodies are
 * compiled via __attribute__((target(...))) and selected per-process with
 * __builtin_cpu_supports, so the same .so is correct everywhere. */
#if defined(__x86_64__) && defined(__GNUC__)
#include <immintrin.h>
#define ST_AVX512 1
static int st_has_avx512(void) {
  static int cached = -1;
  if (cached < 0)
    cached = __builtin_cpu_supports("avx512f") &&
             __builtin_cpu_supports("avx512dq");
  return cached;
}
#define ST_TARGET_AVX512 __attribute__((target("avx512f,avx512dq")))
/* The scalar loops are the only path on non-AVX-512 x86; without
 * -march=native they'd compile to baseline SSE2. target_clones gives them
 * an AVX2 auto-vectorized clone behind the same runtime-dispatch safety. */
#define ST_CLONES __attribute__((target_clones("avx2", "default")))
#else
#define ST_CLONES
#endif

/* Sender half for one leaf: sign-quantize + pack + error feedback, one fused
 * pass. bit = (r <= 0) — zero counts as negative (reference quirk Q3, kept:
 * converged elements oscillate within +/-scale). With s == 0 the leaf idles:
 * bits still record signs (matching the XLA/numpy tiers bit-for-bit) but the
 * residual is untouched. */
#ifdef ST_AVX512
/* Words whose 32 lanes are all live: two 16-lane compares produce the
 * bitmask directly; +/-s is the scale with the mask spliced into the IEEE
 * sign bit (exactly the scalar code's union trick, 16 lanes at a time).
 * Returns the number of whole words processed. */
ST_TARGET_AVX512
static int64_t quantize_leaf_avx512(const float *rin, float *rout, int64_t n,
                                    float s, uint32_t *words) {
  const __m512 vzero = _mm512_setzero_ps();
  const __m512i vs = _mm512_castps_si512(_mm512_set1_ps(s));
  const __m512i vsign = _mm512_set1_epi32((int32_t)0x80000000u);
  int64_t w = 0;
  for (; w < n / 32; w++) {
    const float *p = rin + w * 32;
    float *q = rout + w * 32;
    __m512 v0 = _mm512_loadu_ps(p);
    __m512 v1 = _mm512_loadu_ps(p + 16);
    __mmask16 m0 = _mm512_cmp_ps_mask(v0, vzero, _CMP_LE_OQ);
    __mmask16 m1 = _mm512_cmp_ps_mask(v1, vzero, _CMP_LE_OQ);
    if (s > 0.0f) {
      __m512 d0 = _mm512_castsi512_ps(_mm512_mask_xor_epi32(vs, m0, vs, vsign));
      __m512 d1 = _mm512_castsi512_ps(_mm512_mask_xor_epi32(vs, m1, vs, vsign));
      _mm512_storeu_ps(q, _mm512_sub_ps(v0, d0));
      _mm512_storeu_ps(q + 16, _mm512_sub_ps(v1, d1));
    } else {
      _mm512_storeu_ps(q, v0);
      _mm512_storeu_ps(q + 16, v1);
    }
    words[w] = (uint32_t)m0 | ((uint32_t)m1 << 16);
  }
  return w;
}
#endif

ST_CLONES
static void quantize_leaf(const float *rin, float *rout, int64_t n,
                          int64_t padded, float s, uint32_t *words) {
  int64_t nw = padded / 32;
  int64_t w = 0;
#ifdef ST_AVX512
  if (st_has_avx512()) w = quantize_leaf_avx512(rin, rout, n, s, words);
#endif
  for (; w < nw; w++) {
    uint32_t bits = 0;
    int64_t base = w * 32;
    int64_t lim = n - base;
    if (lim > 32) lim = 32;
    if (s > 0.0f) {
      for (int64_t b = 0; b < lim; b++) {
        float v = rin[base + b];
        uint32_t neg = v <= 0.0f;
        bits |= neg << b;
        rout[base + b] = v - (neg ? -s : s);
      }
    } else {
      for (int64_t b = 0; b < lim; b++) {
        float v = rin[base + b];
        bits |= (uint32_t)(v <= 0.0f) << b;
        rout[base + b] = v;
      }
    }
    /* the caller hands a fresh output buffer: re-establish the all-zero
     * padding invariant on lanes past the live elements */
    for (int64_t b = (lim < 0 ? 0 : lim); b < 32; b++) rout[base + b] = 0.0f;
    words[w] = bits;
  }
}

#ifdef ST_AVX512
/* 16 floats/iter; squares/sums accumulate in 8-lane doubles, so the
 * result is a double-sum like the scalar path (order differs; double
 * accumulation makes the difference vanish below f32 rounding — the
 * tiers tolerate 1-ulp scale differences, see ops/codec_np.py).
 * Returns elements consumed; partials land in amax, ss, sabs. */
ST_TARGET_AVX512
static int64_t scale_partials_leaf_avx512(const float *p, int64_t n,
                                          double *amax, double *ss,
                                          double *sabs) {
  const __m512i vabsmask = _mm512_set1_epi32(0x7FFFFFFF);
  __m512 vamax = _mm512_setzero_ps();
  __m512d vss0 = _mm512_setzero_pd(), vss1 = _mm512_setzero_pd();
  __m512d vsa0 = _mm512_setzero_pd(), vsa1 = _mm512_setzero_pd();
  int64_t j = 0;
  for (; j + 16 <= n; j += 16) {
    __m512 v = _mm512_loadu_ps(p + j);
    __m512 a = _mm512_castsi512_ps(
        _mm512_and_epi32(_mm512_castps_si512(v), vabsmask));
    vamax = _mm512_max_ps(vamax, a);
    __m512d lo = _mm512_cvtps_pd(_mm512_castps512_ps256(v));
    __m512d hi = _mm512_cvtps_pd(_mm512_extractf32x8_ps(v, 1));
    vss0 = _mm512_fmadd_pd(lo, lo, vss0);
    vss1 = _mm512_fmadd_pd(hi, hi, vss1);
    __m512d alo = _mm512_cvtps_pd(_mm512_castps512_ps256(a));
    __m512d ahi = _mm512_cvtps_pd(_mm512_extractf32x8_ps(a, 1));
    vsa0 = _mm512_add_pd(vsa0, alo);
    vsa1 = _mm512_add_pd(vsa1, ahi);
  }
  *amax = _mm512_reduce_max_ps(vamax);
  *ss = _mm512_reduce_add_pd(vss0) + _mm512_reduce_add_pd(vss1);
  *sabs = _mm512_reduce_add_pd(vsa0) + _mm512_reduce_add_pd(vsa1);
  return j;
}
#endif

/* Per-leaf reduction partials for the scale policies, one fused pass per
 * leaf: max|r|, sum(r^2), sum(|r|). Double accumulators make the raw sums
 * overflow-safe by construction (f32 max squared ~1.2e77 << DBL_MAX), where
 * the f32 tiers need the amax-normalization trick (quirk Q9 discussion in
 * ops/codec.compute_scale). The Python caller finishes the policy math. */
ST_CLONES
EXPORT void stc_scale_partials(const float *r, const int64_t *off,
                               const int64_t *ns, int64_t n_leaves,
                               double *out_amax, double *out_ss,
                               double *out_sabs) {
  for (int64_t i = 0; i < n_leaves; i++) {
    const float *p = r + off[i];
    int64_t n = ns[i];
    /* 4-way unrolled accumulators: breaks the serial FP dependency chain so
     * the adds pipeline (a single double accumulator costs ~4 cycles/elem) */
    double amax[4] = {0, 0, 0, 0}, ss[4] = {0, 0, 0, 0}, sabs[4] = {0, 0, 0, 0};
    int64_t j = 0;
#ifdef ST_AVX512
    if (st_has_avx512())
      j = scale_partials_leaf_avx512(p, n, &amax[0], &ss[0], &sabs[0]);
#endif
    for (; j + 4 <= n; j += 4) {
      for (int u = 0; u < 4; u++) {
        double v = p[j + u];
        double a = v < 0 ? -v : v;
        if (a > amax[u]) amax[u] = a;
        ss[u] += v * v;
        sabs[u] += a;
      }
    }
    for (; j < n; j++) {
      double v = p[j];
      double a = v < 0 ? -v : v;
      if (a > amax[0]) amax[0] = a;
      ss[0] += v * v;
      sabs[0] += a;
    }
    double am = amax[0];
    for (int u = 1; u < 4; u++)
      if (amax[u] > am) am = amax[u];
    out_amax[i] = am;
    out_ss[i] = ss[0] + ss[1] + ss[2] + ss[3];
    out_sabs[i] = sabs[0] + sabs[1] + sabs[2] + sabs[3];
  }
}

/* Functional form — reads rin, writes rout (the Python tier's update
 * discipline is replace-not-mutate, so writing to a fresh output buffer
 * saves the 4-byte-per-element input copy an in-place API would force). */
ST_CLONES
EXPORT void stc_quantize(const float *rin, float *rout, const int64_t *off,
                         const int64_t *ns, const int64_t *padded,
                         int64_t n_leaves, const float *scales,
                         uint32_t *words) {
  for (int64_t i = 0; i < n_leaves; i++) {
    quantize_leaf(rin + off[i], rout + off[i], ns[i], padded[i], scales[i],
                  words + off[i] / 32);
  }
}

#ifdef ST_AVX512
/* The packed word IS two __mmask16s: splice each bit into the IEEE sign
 * of a broadcast s (bit set -> -s, reference src/sharedtensor.c:109)
 * and accumulate, 16 lanes per op. Returns whole words processed. */
ST_TARGET_AVX512
static int64_t accumulate_leaf_avx512(float *d, const uint32_t *w,
                                      int64_t full, float s) {
  const __m512i vs = _mm512_castps_si512(_mm512_set1_ps(s));
  const __m512i vsign = _mm512_set1_epi32((int32_t)0x80000000u);
  int64_t k = 0;
  for (; k < full; k++) {
    uint32_t bits = w[k];
    float *dd = d + k * 32;
    __mmask16 m0 = (__mmask16)bits;
    __mmask16 m1 = (__mmask16)(bits >> 16);
    __m512 d0 = _mm512_castsi512_ps(_mm512_mask_xor_epi32(vs, m0, vs, vsign));
    __m512 d1 = _mm512_castsi512_ps(_mm512_mask_xor_epi32(vs, m1, vs, vsign));
    _mm512_storeu_ps(dd, _mm512_add_ps(_mm512_loadu_ps(dd), d0));
    _mm512_storeu_ps(dd + 16, _mm512_add_ps(_mm512_loadu_ps(dd + 16), d1));
  }
  return k;
}
#endif

#ifdef ST_AVX512
/* Fused quantize + next-frame partials: the burst sender needs the NEW
 * residual's scale partials for frame k+1, and they are free to accumulate
 * while frame k's residual values are still in registers — one memory pass
 * instead of quantize-then-rescan (the two-pass shape costs ~40% of the
 * engine's per-frame time at 1 Mi). Returns whole words processed. */
ST_TARGET_AVX512
static int64_t quantize_partials_leaf_avx512(const float *rin, float *rout,
                                             int64_t n, float s,
                                             uint32_t *words, double *amax,
                                             double *ss, double *sabs) {
  const __m512 vzero = _mm512_setzero_ps();
  const __m512i vs = _mm512_castps_si512(_mm512_set1_ps(s));
  const __m512i vsign = _mm512_set1_epi32((int32_t)0x80000000u);
  const __m512i vabsmask = _mm512_set1_epi32(0x7FFFFFFF);
  __m512 vamax = _mm512_setzero_ps();
  __m512d vss0 = _mm512_setzero_pd(), vss1 = _mm512_setzero_pd();
  __m512d vsa0 = _mm512_setzero_pd(), vsa1 = _mm512_setzero_pd();
  int64_t w = 0;
  for (; w < n / 32; w++) {
    const float *p = rin + w * 32;
    float *q = rout + w * 32;
    __m512 v0 = _mm512_loadu_ps(p);
    __m512 v1 = _mm512_loadu_ps(p + 16);
    __mmask16 m0 = _mm512_cmp_ps_mask(v0, vzero, _CMP_LE_OQ);
    __mmask16 m1 = _mm512_cmp_ps_mask(v1, vzero, _CMP_LE_OQ);
    __m512 r0 = v0, r1 = v1;
    if (s > 0.0f) {
      __m512 d0 = _mm512_castsi512_ps(_mm512_mask_xor_epi32(vs, m0, vs, vsign));
      __m512 d1 = _mm512_castsi512_ps(_mm512_mask_xor_epi32(vs, m1, vs, vsign));
      r0 = _mm512_sub_ps(v0, d0);
      r1 = _mm512_sub_ps(v1, d1);
    }
    _mm512_storeu_ps(q, r0);
    _mm512_storeu_ps(q + 16, r1);
    words[w] = (uint32_t)m0 | ((uint32_t)m1 << 16);
    /* partials of the residual just written (scale_partials_leaf_avx512's
     * arithmetic, fused here) */
    __m512 a0 = _mm512_castsi512_ps(
        _mm512_and_epi32(_mm512_castps_si512(r0), vabsmask));
    __m512 a1 = _mm512_castsi512_ps(
        _mm512_and_epi32(_mm512_castps_si512(r1), vabsmask));
    vamax = _mm512_max_ps(vamax, _mm512_max_ps(a0, a1));
    __m512d lo0 = _mm512_cvtps_pd(_mm512_castps512_ps256(r0));
    __m512d hi0 = _mm512_cvtps_pd(_mm512_extractf32x8_ps(r0, 1));
    __m512d lo1 = _mm512_cvtps_pd(_mm512_castps512_ps256(r1));
    __m512d hi1 = _mm512_cvtps_pd(_mm512_extractf32x8_ps(r1, 1));
    vss0 = _mm512_fmadd_pd(lo0, lo0, vss0);
    vss1 = _mm512_fmadd_pd(hi0, hi0, vss1);
    vss0 = _mm512_fmadd_pd(lo1, lo1, vss0);
    vss1 = _mm512_fmadd_pd(hi1, hi1, vss1);
    vsa0 = _mm512_add_pd(
        vsa0, _mm512_cvtps_pd(_mm512_castps512_ps256(a0)));
    vsa1 = _mm512_add_pd(
        vsa1, _mm512_cvtps_pd(_mm512_extractf32x8_ps(a0, 1)));
    vsa0 = _mm512_add_pd(
        vsa0, _mm512_cvtps_pd(_mm512_castps512_ps256(a1)));
    vsa1 = _mm512_add_pd(
        vsa1, _mm512_cvtps_pd(_mm512_extractf32x8_ps(a1, 1)));
  }
  *amax = _mm512_reduce_max_ps(vamax);
  *ss = _mm512_reduce_add_pd(vss0) + _mm512_reduce_add_pd(vss1);
  *sabs = _mm512_reduce_add_pd(vsa0) + _mm512_reduce_add_pd(vsa1);
  return w;
}
#endif

/* Sender step + NEXT frame's scale partials, one fused pass per leaf (see
 * quantize_partials_leaf_avx512). Partials are per-leaf overwrites like
 * stc_scale_partials; live lanes only. Semantics of the quantize half are
 * identical to stc_quantize. */
ST_CLONES
EXPORT void stc_quantize_ef_partials(
    const float *rin, float *rout, const int64_t *off, const int64_t *ns,
    const int64_t *padded, int64_t n_leaves, const float *scales,
    uint32_t *words, double *out_amax, double *out_ss, double *out_sabs) {
  for (int64_t i = 0; i < n_leaves; i++) {
    const float *p = rin + off[i];
    float *q = rout + off[i];
    uint32_t *wp = words + off[i] / 32;
    int64_t n = ns[i], pad = padded[i];
    float s = scales[i];
    double amax = 0, ssum = 0, sabs = 0;
    int64_t w = 0;
#ifdef ST_AVX512
    if (st_has_avx512())
      w = quantize_partials_leaf_avx512(p, q, n, s, wp, &amax, &ssum, &sabs);
#endif
    int64_t nw = pad / 32;
    for (; w < nw; w++) {
      uint32_t bits = 0;
      int64_t base = w * 32;
      int64_t lim = n - base;
      if (lim > 32) lim = 32;
      for (int64_t b = 0; b < (lim < 0 ? 0 : lim); b++) {
        float v = p[base + b];
        uint32_t neg = v <= 0.0f;
        bits |= neg << b;
        float r = s > 0.0f ? v - (neg ? -s : s) : v;
        q[base + b] = r;
        double a = r < 0 ? -(double)r : (double)r;
        if (a > amax) amax = a;
        ssum += (double)r * (double)r;
        sabs += a;
      }
      for (int64_t b = (lim < 0 ? 0 : lim); b < 32; b++) q[base + b] = 0.0f;
      wp[w] = bits;
    }
    out_amax[i] = amax;
    out_ss[i] = ssum;
    out_sabs[i] = sabs;
  }
}

/* Receiver half: accumulate K frames' deltas into delta[total]
 * (delta += s * (1 - 2*bit), reference src/sharedtensor.c:109), then the
 * caller adds delta to each target array. Splitting accumulate/apply keeps
 * the per-array work to one add pass regardless of K. */
ST_CLONES
EXPORT void stc_accumulate_delta(float *delta, const int64_t *off,
                                 const int64_t *ns, const int64_t *padded_unused,
                                 int64_t n_leaves, const float *scales,
                                 const uint32_t *words) {
  (void)padded_unused;
  for (int64_t i = 0; i < n_leaves; i++) {
    float s = scales[i];
    if (s == 0.0f) continue;
    const uint32_t *w = words + off[i] / 32;
    float *d = delta + off[i];
    int64_t n = ns[i];
    int64_t full = n / 32; /* whole words: branch-free, vectorizable */
    int64_t k = 0;
#ifdef ST_AVX512
    if (st_has_avx512()) k = accumulate_leaf_avx512(d, w, full, s);
#endif
    for (; k < full; k++) {
      uint32_t bits = w[k];
      float *dd = d + k * 32;
      float signs[32];
      /* +/-s differ only in the IEEE sign bit: splice the codec bit in */
      for (int b = 0; b < 32; b++) {
        union { float f; uint32_t u; } u;
        u.f = s;
        u.u |= ((bits >> b) & 1u) << 31;
        signs[b] = u.f;
      }
      for (int b = 0; b < 32; b++) dd[b] += signs[b];
    }
    if (n % 32) {
      uint32_t bits = w[full];
      int64_t base = full * 32;
      for (int64_t b = 0; b < n - base; b++) {
        d[base + b] += ((bits >> b) & 1u) ? -s : s;
      }
    }
  }
}

/* values[i] += delta[i] for one target array (live lanes only — padding in
 * both is 0 by invariant, so a full-width add preserves it). Result clamped
 * to +/-3e38 like every other state-mutating path (ops/codec.SAT: no
 * absorbing inf/NaN state, any tier). Branchless min/max — vectorizes. */
ST_CLONES
EXPORT void stc_add_inplace(float *values, const float *delta, int64_t total) {
  for (int64_t i = 0; i < total; i++) {
    float s = values[i] + delta[i];
    s = s > 3.0e38f ? 3.0e38f : s;
    s = s < -3.0e38f ? -3.0e38f : s;
    values[i] = s;
  }
}

/* out[i] = clip(a[i] + delta[i]): the functional-update form of
 * stc_add_inplace. One pass instead of copy-then-add — at table sizes past
 * LLC the host tier is memory-bandwidth-bound and the extra copy pass was
 * ~1/3 of the apply cost (measured at 16 Mi elements). */
ST_CLONES
EXPORT void stc_add_to(float *out, const float *a, const float *delta,
                       int64_t total) {
  for (int64_t i = 0; i < total; i++) {
    float s = a[i] + delta[i];
    s = s > 3.0e38f ? 3.0e38f : s;
    s = s < -3.0e38f ? -3.0e38f : s;
    out[i] = s;
  }
}

#ifdef ST_AVX512
ST_TARGET_AVX512
static int64_t apply_leaf_avx512(const float *in, float *out,
                                 const uint32_t *w, int64_t full, float s) {
  const __m512i vs = _mm512_castps_si512(_mm512_set1_ps(s));
  const __m512i vsign = _mm512_set1_epi32((int32_t)0x80000000u);
  const __m512 vmax = _mm512_set1_ps(3.0e38f);
  const __m512 vmin = _mm512_set1_ps(-3.0e38f);
  int64_t k = 0;
  for (; k < full; k++) {
    uint32_t bits = w[k];
    const float *pp = in + k * 32;
    float *qq = out + k * 32;
    __mmask16 m0 = (__mmask16)bits;
    __mmask16 m1 = (__mmask16)(bits >> 16);
    __m512 d0 = _mm512_castsi512_ps(_mm512_mask_xor_epi32(vs, m0, vs, vsign));
    __m512 d1 = _mm512_castsi512_ps(_mm512_mask_xor_epi32(vs, m1, vs, vsign));
    __m512 r0 = _mm512_add_ps(_mm512_loadu_ps(pp), d0);
    __m512 r1 = _mm512_add_ps(_mm512_loadu_ps(pp + 16), d1);
    r0 = _mm512_max_ps(_mm512_min_ps(r0, vmax), vmin);
    r1 = _mm512_max_ps(_mm512_min_ps(r1, vmax), vmin);
    _mm512_storeu_ps(qq, r0);
    _mm512_storeu_ps(qq + 16, r1);
  }
  return k;
}
#endif

/* Fully fused single-frame apply: out = clip(in + s*(1-2*bit)) in ONE pass,
 * no delta buffer, no copy — the K=1 receive path (the common case: one
 * incoming frame applied to values + each other link's residual). Padding
 * lanes beyond ns[i] are copied verbatim (0 by invariant). */
ST_CLONES
EXPORT void stc_apply_frame(const float *vin, float *vout, const int64_t *off,
                            const int64_t *ns, const int64_t *padded,
                            int64_t n_leaves, const float *scales,
                            const uint32_t *words) {
  for (int64_t i = 0; i < n_leaves; i++) {
    const float *in = vin + off[i];
    float *out = vout + off[i];
    const uint32_t *w = words + off[i] / 32;
    int64_t n = ns[i], pad = padded[i];
    float s = scales[i];
    if (s == 0.0f) { /* idle leaf: pure copy */
      memcpy(out, in, (size_t)pad * sizeof(float));
      continue;
    }
    int64_t full = n / 32;
    int64_t k = 0;
#ifdef ST_AVX512
    if (st_has_avx512()) k = apply_leaf_avx512(in, out, w, full, s);
#endif
    for (; k < full; k++) {
      uint32_t bits = w[k];
      for (int b = 0; b < 32; b++) {
        float v = in[k * 32 + b] + (((bits >> b) & 1u) ? -s : s);
        v = v > 3.0e38f ? 3.0e38f : v;
        v = v < -3.0e38f ? -3.0e38f : v;
        out[k * 32 + b] = v;
      }
    }
    int64_t base = full * 32;
    if (n % 32) {
      uint32_t bits = w[full];
      for (int64_t b = 0; b < n - base; b++) {
        float v = in[base + b] + (((bits >> b) & 1u) ? -s : s);
        v = v > 3.0e38f ? 3.0e38f : v;
        v = v < -3.0e38f ? -3.0e38f : v;
        out[base + b] = v;
      }
      for (int64_t b = n - base; b < 32 && base + b < pad; b++)
        out[base + b] = in[base + b];
      base += 32;
    }
    if (base < pad)
      memcpy(out + base, in + base, (size_t)(pad - base) * sizeof(float));
  }
}

/* Local additive update, sanitized (quirk Q9 fix — one NaN in the reference
 * poisons every replica through the flood): u is pre-masked by the caller;
 * NaN -> 0, +/-inf and sums clamped to +/-3e38. */
ST_CLONES
EXPORT void stc_accumulate_update(float *a, const float *u, int64_t total) {
  for (int64_t i = 0; i < total; i++) {
    float x = u[i];
    if (x != x) x = 0.0f; /* NaN */
    if (x > 3.0e38f) x = 3.0e38f;
    if (x < -3.0e38f) x = -3.0e38f;
    float s = a[i] + x;
    if (s > 3.0e38f) s = 3.0e38f;
    if (s < -3.0e38f) s = -3.0e38f;
    a[i] = s;
  }
}

/* Functional one-pass form: out = clip(a + sanitize(u)) on live lanes,
 * out = a on padding (so a raw update's padding garbage never enters the
 * buffer — the caller no longer pre-masks or copies). Replaces the
 * copy-then-inplace pattern, which cost an extra full memory pass per
 * target array (the add path runs once per link residual plus the replica). */
ST_CLONES
EXPORT void stc_accumulate_update_to(float *vout, const float *a,
                                     const float *u, const int64_t *off,
                                     const int64_t *ns, const int64_t *padded,
                                     int64_t n_leaves) {
  for (int64_t i = 0; i < n_leaves; i++) {
    const float *ap = a + off[i];
    const float *up = u + off[i];
    float *op = vout + off[i];
    int64_t n = ns[i], pad = padded[i];
    for (int64_t j = 0; j < n; j++) {
      float x = up[j];
      if (x != x) x = 0.0f; /* NaN */
      if (x > 3.0e38f) x = 3.0e38f;
      if (x < -3.0e38f) x = -3.0e38f;
      float s = ap[j] + x;
      if (s > 3.0e38f) s = 3.0e38f;
      if (s < -3.0e38f) s = -3.0e38f;
      op[j] = s;
    }
    if (n < pad)
      memcpy(op + n, ap + n, (size_t)(pad - n) * sizeof(float));
  }
}
