// st_annotations.h: clang thread-safety annotations for the native tier.
//
// The r11/r12 review rounds each hand-found a real data race in these files
// (the codec-pool seqlock tearing, the plain-int sleepers, the replayed-STTS
// stripe refcount) — human review was the only race detector the native tier
// had. These macros make the lock discipline machine-checked: every
// mutex-protected field carries ST_GUARDED_BY, every
// must-hold-the-lock-to-call function carries ST_REQUIRES, and
// `make -C native analyze` compiles all three files under clang's
// -Wthread-safety -Werror (tests/test_static_analysis.py smoke-runs it when
// clang is present; the tier-1 gcc build sees only no-op macros).
//
// Lock hierarchy (documented here because the annotations force it to be
// written down; ST_ACQUIRED_AFTER encodes the edges clang can check):
//
//   stengine.cpp   Engine::mu  ->  Engine::add_mu          (fold_pending)
//                  Engine::mu  ->  TxPool::mu              (rollback/ACK unref)
//                  Engine::mu  ->  transport queue mutexes (flush_acks / FRESH
//                                  beats send with zero timeout from under mu)
//                  Engine::wmu and Engine::cmu are leaves (nothing is
//                  acquired under them).
//   sttransport.cpp  Node::mu, Node::ev_mu, Node::data_mu, Link::rmu,
//                  Link::fault_mu and the queue/pool mutexes are mutually
//                  unordered leaves — no path acquires one under another
//                  (kill_link takes Link::rmu and Node::mu SEQUENTIALLY,
//                  never nested).
//                  r14 additions, both leaves: stshm::Lane::tx_mu (the
//                  shm ring's single-writer serialization across the
//                  stripe-death promotion window; held across a whole
//                  record write, including its bounded futex waits — the
//                  ring head/tail atomics themselves are cross-process
//                  and carry their ordering in the futex publish
//                  protocol, not in any mutex) and Node::loan_mu (the
//                  recv_zc loan registry; taken sequentially with
//                  Node::mu, never nested). The shm segment's shared
//                  header fields (joined/closed, Ring head/tail/seq
//                  words) are interprocess atomics outside any
//                  capability the analysis can see — their discipline is
//                  documented at stshm::RingCtl and checked by the TSan
//                  shm arm instead.
//   stcodec.c      g_pool.job_mu -> g_pool.mu (submitter wake/completion
//                  sleep); workers take g_pool.mu alone.
//
// C++ callers use StMutex / StLockGuard / StUniqueLock below — thin
// wrappers over std::mutex whose lock/unlock methods carry the acquire/
// release attributes (libstdc++'s std::mutex is not a clang "capability",
// so guarded-by on it would not type-check). C callers (stcodec.c) define
// their own annotated pthread wrapper next to the pool; only the macros
// live here.

#ifndef ST_ANNOTATIONS_H_
#define ST_ANNOTATIONS_H_

#if defined(__clang__) && defined(__has_attribute)
#if __has_attribute(capability)
#define ST_TSA_(x) __attribute__((x))
#endif
#endif
#ifndef ST_TSA_
#define ST_TSA_(x)  // no-op off clang (gcc builds see plain declarations)
#endif

#define ST_CAPABILITY(x) ST_TSA_(capability(x))
#define ST_SCOPED_CAPABILITY ST_TSA_(scoped_lockable)
// In C, clang does not late-parse thread-safety attribute arguments, so
// a struct member cannot reference a sibling mutex member ("use of
// undeclared identifier 'mu'") — which is exactly what stcodec.c's
// g_pool fields need. The C TU keeps the capability/acquire/release
// CONTRACTS (parameter references parse fine); its guarded-by
// discipline is checked by the TSan arm instead.
#if defined(__cplusplus)
#define ST_GUARDED_BY(x) ST_TSA_(guarded_by(x))
#define ST_PT_GUARDED_BY(x) ST_TSA_(pt_guarded_by(x))
#else
#define ST_GUARDED_BY(x)
#define ST_PT_GUARDED_BY(x)
#endif
#define ST_ACQUIRED_BEFORE(...) ST_TSA_(acquired_before(__VA_ARGS__))
#define ST_ACQUIRED_AFTER(...) ST_TSA_(acquired_after(__VA_ARGS__))
#define ST_REQUIRES(...) ST_TSA_(requires_capability(__VA_ARGS__))
#define ST_ACQUIRE(...) ST_TSA_(acquire_capability(__VA_ARGS__))
#define ST_RELEASE(...) ST_TSA_(release_capability(__VA_ARGS__))
#define ST_TRY_ACQUIRE(...) ST_TSA_(try_acquire_capability(__VA_ARGS__))
#define ST_EXCLUDES(...) ST_TSA_(locks_excluded(__VA_ARGS__))
#define ST_RETURN_CAPABILITY(x) ST_TSA_(lock_returned(x))
#define ST_NO_THREAD_SAFETY_ANALYSIS ST_TSA_(no_thread_safety_analysis)

#ifdef __cplusplus

#include <mutex>

// std::mutex with the capability attribute, so fields can be
// ST_GUARDED_BY(mu) and functions ST_REQUIRES(mu). native() exposes the
// underlying std::mutex for condition_variable waits ONLY — a wait
// releases and re-acquires internally, which is invisible to (and fine
// for) the analysis: the capability is held on both sides of the call.
class ST_CAPABILITY("mutex") StMutex {
 public:
  void lock() ST_ACQUIRE() { mu_.lock(); }
  void unlock() ST_RELEASE() { mu_.unlock(); }
  bool try_lock() ST_TRY_ACQUIRE(true) { return mu_.try_lock(); }
  std::mutex& native() { return mu_; }

 private:
  std::mutex mu_;
};

// std::lock_guard twin.
class ST_SCOPED_CAPABILITY StLockGuard {
 public:
  explicit StLockGuard(StMutex& mu) ST_ACQUIRE(mu) : mu_(mu) { mu_.lock(); }
  ~StLockGuard() ST_RELEASE() { mu_.unlock(); }
  StLockGuard(const StLockGuard&) = delete;
  StLockGuard& operator=(const StLockGuard&) = delete;

 private:
  StMutex& mu_;
};

// std::unique_lock twin for the condvar / manual unlock-relock sites.
// Pass native() to condition_variable::wait*; the lock state the condvar
// hands back matches what the analysis assumes (held).
class ST_SCOPED_CAPABILITY StUniqueLock {
 public:
  explicit StUniqueLock(StMutex& mu) ST_ACQUIRE(mu)
      : lk_(mu.native()) {}
  ~StUniqueLock() ST_RELEASE() {}
  void lock() ST_ACQUIRE() { lk_.lock(); }
  void unlock() ST_RELEASE() { lk_.unlock(); }
  std::unique_lock<std::mutex>& native() { return lk_; }
  StUniqueLock(const StUniqueLock&) = delete;
  StUniqueLock& operator=(const StUniqueLock&) = delete;

 private:
  std::unique_lock<std::mutex> lk_;
};

#endif  // __cplusplus
#endif  // ST_ANNOTATIONS_H_
