// sttransport: native host transport for shared-tensor-tpu.
//
// TPU-native re-design of the reference's communication layers (the 477-line
// C module's L1 robust I/O, L3 link engines, L4 tree topology — see SURVEY.md
// §1; reference src/sharedtensor.c:53-104, :113-189, :192-332). The codec
// math itself lives on the TPU (Pallas kernels); this library owns only the
// wire: the self-organizing binary-tree overlay, framed full-duplex streaming
// per link, join/redirect membership, bandwidth pacing, liveness, and
// metrics. Frames are opaque byte payloads to this layer.
//
// Deliberate fixes over the reference (SURVEY.md Appendix A):
//  - any socket error tears down ONE link and emits an event instead of
//    exit(-1) for the whole process (quirks Q8; README.md:33 TODO);
//  - a dropped uplink re-joins through the rendezvous automatically;
//  - outgoing bandwidth can be capped per link (token bucket; README.md:31);
//  - configurable listen backlog (Q10), clean shutdown for connected nodes.
//
// Two wire modes:
//  - native (default): length-prefixed frames [u32le len][payload]; len==0 is
//    a keepalive. Join handshake: client sends "STT3" + u32le payload_hint;
//    server replies 'Y' (accept) or 'N' + 16-byte IPv4 sockaddr redirect.
//  - wire-compat: byte-exact reference protocol for interop with C peers
//    (SURVEY.md §2.3): no hello, fixed-size frames [f32 scale][ceil(n/8) bit
//    mask], join reply 'Y' / 'N'+sockaddr, idle links emit one zero-scale
//    frame per second (reference quirk Q2 behavior, required for liveness).
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this image).

#include <arpa/inet.h>
#include <fcntl.h>
#include <linux/futex.h>
#include <signal.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/syscall.h>
#include <sys/uio.h>
#include <time.h>
#include <unistd.h>

// ST_ANALYZE_NO_SIMD: the clang front-end analyzer (-Wthread-safety,
// tools/analyze_clang.py) cannot parse gcc's intrinsics headers; it
// analyzes the scalar reference paths instead. Never set by any build.
#if defined(__x86_64__) && defined(__SSE2__) && !defined(ST_ANALYZE_NO_SIMD)
#include <emmintrin.h>  // NT stores for the shm ring bulk copies
#endif

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <new>
#include <string>
#include <thread>
#include <vector>

#include "st_annotations.h"  // clang -Wthread-safety vocabulary (no-op on gcc)
#include "st_cv.h"           // system-clock condvar deadlines (TSan arm)

// Process-wide crash point (ST_FAULT_CRASH="name:N"): _exit(17) on the Nth
// arrival at the named point. Parsed once; thread-safe countdown. Defined
// ONCE for the whole .so and shared with stengine.cpp's protocol points
// (mid-burst, between-apply-and-ack) — a per-translation-unit copy would
// split the parse/countdown state, so a point name served by both files
// would fire at the wrong Nth arrival.
extern "C" __attribute__((visibility("default"))) void st_fault_crash_point(
    const char* name) {
  // Hot path first: every engine/transport data loop in the process calls
  // this per message, so the UNARMED case (production default) must be a
  // single relaxed atomic load — never the shared mutex, which would be a
  // process-global serialization point across all nodes' threads.
  static std::atomic<int> armed{-1};  // -1 unparsed, 0 unarmed, 1 armed
  int a = armed.load(std::memory_order_relaxed);
  if (a == 0) return;
  static StMutex mu;
  static std::string point;    // under mu (function-locals cannot carry
  static long remaining = 0;   // ST_GUARDED_BY; the guard below is the law)
  StLockGuard lk(mu);
  if (armed.load(std::memory_order_relaxed) < 0) {
    const char* env = getenv("ST_FAULT_CRASH");
    if (env && *env) {
      std::string s(env);
      size_t c = s.find(':');
      point = c == std::string::npos ? s : s.substr(0, c);
      remaining = c == std::string::npos ? 1 : atol(s.c_str() + c + 1);
      if (remaining < 1) remaining = 1;
    }
    armed.store(point.empty() ? 0 : 1, std::memory_order_relaxed);
  }
  if (point.empty() || point != name) return;
  if (--remaining <= 0) _exit(17);
}

// ---- obs event ring (r08 tentpole) ---------------------------------------
//
// Lock-free per-thread rings of 32-byte timestamped protocol events, the
// native half of the cross-tier timeline (shared_tensor_tpu/obs/events.py
// defines the code names; the numeric codes here are ABI). Design:
//
//  - each EMITTING thread owns one SPSC ring (thread_local holder): the
//    writer touches only its own head (release store), the drainer only
//    tails (release store) — no locks, no CAS on the hot path. A full
//    ring DROPS the event and counts the drop (g_dropped), so a stalled
//    drainer degrades accounting, never the data plane.
//  - rings are registered in a global list under a mutex taken only at
//    thread birth and at drain time (both rare); rings are never freed —
//    a ring whose thread exited is marked retired and re-adopted by the
//    next new thread after its leftover events drain.
//  - timestamps are CLOCK_MONOTONIC ns, the same clock CPython's
//    time.monotonic_ns() reads on Linux, so native and Python events merge
//    by plain sort (st_obs_now_ns exports the clock for agreement checks).
//  - ST_OBS=0 in the environment (or st_obs_set_enabled(0)) turns emission
//    into one relaxed atomic load — the production-off cost.
//
// Shared with stengine.cpp (which imports st_obs_emit/st_node_obs_id):
// defined ONCE here for the same reason as st_fault_crash_point above.
namespace stobs {

constexpr uint32_t kEvRingCap = 2048;  // events per thread ring

struct EventRec {  // the 32-byte drain ABI record (obs/events.py _EVENT_FMT)
  uint64_t t_ns;
  uint32_t node_id;
  uint32_t code;
  int32_t link;
  uint32_t reserved;
  uint64_t arg;
};
static_assert(sizeof(EventRec) == 32, "obs event record is 32-byte ABI");

struct Ring {
  std::atomic<uint64_t> head{0};  // writer-owned
  std::atomic<uint64_t> tail{0};  // drainer-owned
  std::atomic<bool> live{false};  // owned by a running thread
  EventRec ev[kEvRingCap];
};

StMutex g_reg_mu;            // ring registration + drain (rare paths only)
// never freed; retired rings are re-adopted (ring INTERNALS are the SPSC
// head/tail atomics — only the list itself needs the registration mutex)
std::vector<Ring*> g_rings ST_GUARDED_BY(g_reg_mu);
std::atomic<int> g_enabled{[] {
  const char* e = getenv("ST_OBS");
  return (e && e[0] == '0' && !e[1]) ? 0 : 1;
}()};
std::atomic<uint64_t> g_dropped{0};
// Node obs ids must be unique across the PROCESSES of a loopback cluster,
// not just within one — the r09 digest keys its per-node breakdown and the
// trace context keys update origins on this id. Layout: 12 pid bits +
// 12 local bits = 24 bits, EXACTLY the origin field the trace record
// packs (origin << 8 | hop in a u32) — the local counter wraps INSIDE its
// pid block so an id can never exceed 2^24 (a spill past it would be
// silently truncated in every trace event, conflating origins). 4096
// nodes per process before in-block reuse; a long pytest session creates
// hundreds, not thousands. Cross-process risk left: two pids equal mod
// 4096 in ONE tree (1/4096 per pair — accepted, documented).
std::atomic<uint32_t> g_next_node_local{0};
const uint32_t g_node_id_base = ((uint32_t)getpid() & 0xFFFu) << 12;

inline uint64_t now_ns() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (uint64_t)ts.tv_sec * 1000000000ull + (uint64_t)ts.tv_nsec;
}

// Thread-local ring ownership: adopt a retired ring (its undrained tail is
// preserved) or register a fresh one; retire at thread exit. Registration
// is once per thread lifetime — never on the emit path.
struct RingHolder {
  Ring* r;
  RingHolder() {
    StLockGuard lk(g_reg_mu);
    for (Ring* cand : g_rings) {
      // acquire pairs with the dead owner's release store in ~RingHolder:
      // the adopter must observe the old thread's final head/record
      // stores before writing its own events, or a stale head could
      // overwrite undrained records (a relaxed load has no such edge)
      if (!cand->live.load(std::memory_order_acquire)) {
        cand->live.store(true, std::memory_order_relaxed);
        r = cand;
        return;
      }
    }
    r = new Ring();
    r->live.store(true, std::memory_order_relaxed);
    g_rings.push_back(r);
  }
  ~RingHolder() { r->live.store(false, std::memory_order_release); }
};

// event codes (ABI; obs/events.py CODE_NAMES is the authoritative mirror).
// 1..4 reuse the membership Event kinds verbatim.
// maybe_unused: several are ABI documentation — the emit sites build the
// code inline (clang's -Wunused-const-variable would flag them).
[[maybe_unused]] constexpr uint32_t kEvRetransmit = 10;
[[maybe_unused]] constexpr uint32_t kEvBlackhole = 11;
[[maybe_unused]] constexpr uint32_t kEvQuarantine = 12;
[[maybe_unused]] constexpr uint32_t kEvWindowStall = 13;
[[maybe_unused]] constexpr uint32_t kEvDedupDiscard = 14;
[[maybe_unused]] constexpr uint32_t kEvSeal = 15;
constexpr uint32_t kEvFaultDrop = 20;
constexpr uint32_t kEvFaultDup = 21;
constexpr uint32_t kEvFaultCorrupt = 22;
constexpr uint32_t kEvFaultTruncate = 23;
constexpr uint32_t kEvFaultDelay = 24;
constexpr uint32_t kEvFaultStall = 25;
constexpr uint32_t kEvFaultSever = 26;
// 32 (precision_shift) is emitted by stengine.cpp; 33 marks one stripe of
// a striped link dying (arg = stripe index) while the link degrades to
// the survivors.
constexpr uint32_t kEvStripeDown = 33;
// r14 same-host shared-memory lane: 34 fires once when a link's data plane
// switches onto its shm rings (arg = ring bytes per direction); 35 when a
// negotiated attach fails validation and the link stays on TCP (arg = an
// errno-ish reason code — 1 open, 2 map, 3 header/token mismatch).
constexpr uint32_t kEvShmLaneUp = 34;
constexpr uint32_t kEvShmFallback = 35;
// 30 (trace_apply) and 31 (sub_attach, r10 subscriber link mode) are
// emitted by stengine.cpp; listed in obs/events.py CODE_NAMES like the
// rest — the numeric values are ABI across all three surfaces.
constexpr uint32_t kEvSubAttach = 31;
static_assert(kEvSubAttach == 31, "ABI code mirrored in obs/events.py");

}  // namespace stobs

extern "C" __attribute__((visibility("default"))) uint64_t st_obs_now_ns() {
  return stobs::now_ns();
}

extern "C" __attribute__((visibility("default"))) void st_obs_set_enabled(
    int32_t on) {
  stobs::g_enabled.store(on ? 1 : 0, std::memory_order_relaxed);
}

extern "C" __attribute__((visibility("default"))) uint64_t st_obs_dropped() {
  return stobs::g_dropped.load(std::memory_order_relaxed);
}

// Emission gate as an ABI call: the engine's r09 trace bookkeeping (clock
// reads, per-message hops/staleness accounting) keys off the same flag as
// ring emission, so the obs-overhead bench's paired A/B toggle
// (st_obs_set_enabled) covers the trace-stamping cost too.
extern "C" __attribute__((visibility("default"))) int32_t
st_obs_is_enabled() {
  return stobs::g_enabled.load(std::memory_order_relaxed);
}

// Record one event on the calling thread's ring. Cheap enough to leave on
// in production (one relaxed load when disabled; one clock read + one
// 32-byte store when armed) — and RARE by design: every call site is a
// protocol/recovery/fault event, never a per-element loop (the r09
// trace_apply events are per accepted wire MESSAGE, still orders of
// magnitude below per-element). ``extra`` lands in the record's fourth
// word (obs/events.py Event.extra) — r09 packs (origin_id << 8 | hops)
// there so one record carries a full trace-hop observation.
extern "C" __attribute__((visibility("default"))) void st_obs_emit2(
    uint32_t node_id, uint32_t code, int32_t link, uint64_t arg,
    uint32_t extra) {
  if (!stobs::g_enabled.load(std::memory_order_relaxed)) return;
  thread_local stobs::RingHolder tl;
  stobs::Ring* r = tl.r;
  uint64_t h = r->head.load(std::memory_order_relaxed);
  if (h - r->tail.load(std::memory_order_acquire) >= stobs::kEvRingCap) {
    stobs::g_dropped.fetch_add(1, std::memory_order_relaxed);
    return;
  }
  stobs::EventRec& e = r->ev[h % stobs::kEvRingCap];
  e.t_ns = stobs::now_ns();
  e.node_id = node_id;
  e.code = code;
  e.link = link;
  e.reserved = extra;
  e.arg = arg;
  r->head.store(h + 1, std::memory_order_release);
}

extern "C" __attribute__((visibility("default"))) void st_obs_emit(
    uint32_t node_id, uint32_t code, int32_t link, uint64_t arg) {
  st_obs_emit2(node_id, code, link, arg, 0);
}

// Drain every thread's ring into buf (whole 32-byte records only); returns
// bytes written. Leftovers stay ring-buffered for the next drain. The
// registration mutex serializes concurrent drainers (Python side calls
// this from peers' recv loops); writers never touch it.
extern "C" __attribute__((visibility("default"))) int32_t st_obs_drain(
    uint8_t* buf, int32_t cap_bytes) {
  int32_t written = 0;
  StLockGuard lk(stobs::g_reg_mu);
  for (stobs::Ring* r : stobs::g_rings) {
    uint64_t t = r->tail.load(std::memory_order_relaxed);
    uint64_t h = r->head.load(std::memory_order_acquire);
    while (t < h &&
           cap_bytes - written >= (int32_t)sizeof(stobs::EventRec)) {
      std::memcpy(buf + written, &r->ev[t % stobs::kEvRingCap],
                  sizeof(stobs::EventRec));
      written += (int32_t)sizeof(stobs::EventRec);
      t++;
    }
    r->tail.store(t, std::memory_order_release);
  }
  return written;
}

// ---- r14 same-host shared-memory lane ------------------------------------
//
// When both endpoints of a link live on one host (negotiated at the Python
// tier's SYNC/WELCOME hello — compat.SYNC_FLAG_SHM + boot-id match, the
// same tolerant-extension discipline as every capability since r09), the
// link's DATA plane moves into a mapped /dev/shm segment: one SPSC byte
// ring per direction, records framed [u32 len][u64 stripe_seq][payload],
// futex wake with spin-before-sleep. The TCP connection STAYS UP as the
// control/teardown/liveness channel — keepalives, join/seq semantics,
// SNAP/RESUME, quarantine/carry/re-graft are all untouched; the lane
// slots in below the wire-seq layer exactly as r11 striping did.
//
// Ordering across the lane switch:
//  - striped links: every record carries the message's stripe seq, so the
//    ring feeds the SAME reassembly window as the sockets
//    (deliver_striped) — in-flight TCP messages and ring records
//    interleave correctly with no barrier at all;
//  - unstriped links: the single sender writes one SWITCH marker
//    ([u32 kShmSwitchLen], a length no real frame can have) as its LAST
//    data-plane byte on TCP, then moves to the ring; the receiver enables
//    ring delivery only when the marker arrives in-stream, so the
//    TCP-before / ring-after order is exact. The marker is only ever sent
//    after a successful shm attach, i.e. never to a pre-r14 peer.
//
// Messages LARGER than the ring stream through it: the writer publishes
// the record header, then payload chunks as space frees; the reader
// drains chunks into its rx buffer as they appear. The ring therefore
// bounds memory, not message size ("slots sized for max traced sign2
// bursts" degrades gracefully when a burst outgrows the default).
//
// Teardown: either side stores hdr->closed and futex-wakes all wait
// words (kill_link does this); a peer death is detected by the TCP
// control channel exactly as before and tears the lane down with the
// link. The segment file is unlinked by the JOINER the moment it maps
// (leak-proof: after that the name cannot outlive the two mappings); the
// creator unlinks at teardown if the joiner never arrived.
namespace stshm {

constexpr uint64_t kMagic = 0x535453484D313400ull;  // "STSHM14\0"
constexpr uint32_t kVersion = 1;
constexpr uint32_t kRecHdr = 12;  // u32 len + u64 sseq
// SWITCH marker length value (unstriped links): above kMaxPayload, so it
// can never collide with a real frame length.
constexpr uint32_t kShmSwitchLen = 0xFFFFFFFDu;
constexpr int kSpins = 2000;  // spin-before-sleep iterations

inline int futex_wait(std::atomic<uint32_t>* w, uint32_t val,
                      long timeout_ms) {
  timespec ts;
  ts.tv_sec = timeout_ms / 1000;
  ts.tv_nsec = (timeout_ms % 1000) * 1000000L;
  // non-PRIVATE futex: the word lives in a shared mapping, the waiter and
  // waker are different processes
  return (int)syscall(SYS_futex, (uint32_t*)w, FUTEX_WAIT, val, &ts,
                      nullptr, 0);
}

inline void futex_wake_all(std::atomic<uint32_t>* w) {
  syscall(SYS_futex, (uint32_t*)w, FUTEX_WAKE, INT32_MAX, nullptr, nullptr,
          0);
}

// One direction's control block. head/tail are BYTE positions (monotonic
// u64; offset = pos % ring_bytes). head_seq/tail_seq are the futex words
// (bumped on every publish/consume). *_waiting gates the wake syscall so
// the uncontended fast path never enters the kernel.
struct alignas(64) RingCtl {
  std::atomic<uint64_t> head;
  std::atomic<uint32_t> head_seq;
  std::atomic<uint32_t> rd_waiting;
  char pad0[64 - 16];
  std::atomic<uint64_t> tail;
  std::atomic<uint32_t> tail_seq;
  std::atomic<uint32_t> wr_waiting;
  char pad1[64 - 16];
};
static_assert(sizeof(RingCtl) == 128, "two cachelines, no false sharing");

// Segment header (one page); ring data follows at kDataOff and
// kDataOff + ring_bytes. ring[0] carries creator->joiner, ring[1]
// joiner->creator.
struct Hdr {
  uint64_t magic;
  uint32_t version;
  uint32_t ring_bytes;
  uint64_t token;
  std::atomic<uint32_t> joined;  // joiner stores 1 after validating
  std::atomic<uint32_t> closed;  // either side stores 1 at teardown
  char pad[128 - 32];
  RingCtl ring[2];
};
constexpr size_t kDataOff = 4096;
static_assert(sizeof(Hdr) <= kDataOff, "header fits the first page");
static_assert(std::atomic<uint64_t>::is_always_lock_free &&
                  std::atomic<uint32_t>::is_always_lock_free,
              "cross-process atomics must be lock-free");

// One mapped lane attached to a Link. tx/rx pick the direction by role.
struct Lane {
  Hdr* hdr = nullptr;
  uint8_t* data[2] = {nullptr, nullptr};
  size_t map_len = 0;
  uint32_t ring_bytes = 0;
  int creator = 0;  // 1 = we created (tx on ring[0]), 0 = joined (ring[1])
  std::string name;  // /dev/shm basename (creator keeps it for unlink)
  std::atomic<bool> marker_sent{false};  // unstriped: SWITCH written (tx)
  std::atomic<bool> rx_go{false};  // delivery enabled (striped: at map)
  std::atomic<bool> ev_emitted{false};
  // The ring is SPSC; the single writer is normally the lowest live
  // stripe's sender thread. During a stripe death the writer role
  // PROMOTES to the next live stripe, and the old and new writer can
  // briefly overlap — tx_mu serializes whole records across that window
  // (uncontended in steady state; record order across writers is
  // reassembled by stripe seq exactly like socket stripes). Guards the
  // tx ring's head position and record integrity; a leaf in the lock
  // hierarchy (nothing is acquired under it).
  StMutex tx_mu;
  // lane counters (st_node_shm_stats; bytes/frames also fold into the
  // link's existing wire counters so the taxonomy holds across lanes)
  std::atomic<uint64_t> msgs_out{0}, msgs_in{0};
  std::atomic<uint64_t> bytes_out{0}, bytes_in{0};
  std::atomic<uint64_t> tx_waits{0}, rx_waits{0};

  RingCtl& tx_ctl() { return hdr->ring[creator ? 0 : 1]; }
  RingCtl& rx_ctl() { return hdr->ring[creator ? 1 : 0]; }
  uint8_t* tx_data() { return data[creator ? 0 : 1]; }
  uint8_t* rx_data() { return data[creator ? 1 : 0]; }

  // tx is live once both sides are mapped (the joiner publishes
  // hdr->joined; for the joiner itself that is immediate)
  bool tx_ready() {
    return hdr && hdr->closed.load(std::memory_order_relaxed) == 0 &&
           hdr->joined.load(std::memory_order_acquire) != 0;
  }

  void close_and_wake() {
    if (!hdr) return;
    hdr->closed.store(1, std::memory_order_release);
    for (int i = 0; i < 2; i++) {
      futex_wake_all(&hdr->ring[i].head_seq);
      futex_wake_all(&hdr->ring[i].tail_seq);
    }
  }

  ~Lane() {
    if (hdr) {
      if (creator && hdr->joined.load(std::memory_order_relaxed) == 0 &&
          !name.empty()) {
        // joiner never arrived: reclaim the name (the joiner unlinks on a
        // successful map — see st_node_shm_join)
        std::string p = "/dev/shm/" + name;
        ::unlink(p.c_str());
      }
      ::munmap((void*)hdr, map_len);
    }
  }
};

// Non-temporal bulk copy INTO the ring: the destination is only ever
// read by the PEER process (another core, through L3/DRAM), so regular
// stores waste a full read-for-ownership stream on bytes we will never
// look at — at 4 MiB messages that is a third of the copy's memory
// traffic. Weakly-ordered NT stores REQUIRE an sfence before the head
// publish (shm_write_record does it); the scalar head/tail protocol is
// untouched.
inline void nt_copy(uint8_t* dst, const uint8_t* src, size_t n) {
#if defined(__x86_64__) && defined(__SSE2__) && !defined(ST_ANALYZE_NO_SIMD)
  if (n >= 256) {
    // align dst to 16 for the streaming stores
    size_t head = ((uintptr_t)dst & 15) ? 16 - ((uintptr_t)dst & 15) : 0;
    if (head) {
      std::memcpy(dst, src, head);
      dst += head;
      src += head;
      n -= head;
    }
    while (n >= 64) {
      __m128i a, b, c, d;
      std::memcpy(&a, src, 16);
      std::memcpy(&b, src + 16, 16);
      std::memcpy(&c, src + 32, 16);
      std::memcpy(&d, src + 48, 16);
      _mm_stream_si128((__m128i*)dst, a);
      _mm_stream_si128((__m128i*)(dst + 16), b);
      _mm_stream_si128((__m128i*)(dst + 32), c);
      _mm_stream_si128((__m128i*)(dst + 48), d);
      dst += 64;
      src += 64;
      n -= 64;
    }
  }
#endif
  std::memcpy(dst, src, n);
}

// wrap-aware copies between a ring's data area and a flat buffer
inline void ring_put(uint8_t* base, uint32_t rb, uint64_t pos,
                     const uint8_t* src, size_t n) {
  size_t off = (size_t)(pos % rb);
  size_t first = std::min(n, (size_t)rb - off);
  nt_copy(base + off, src, first);
  if (n > first) nt_copy(base, src + first, n - first);
}

inline void ring_get(const uint8_t* base, uint32_t rb, uint64_t pos,
                     uint8_t* dst, size_t n) {
  size_t off = (size_t)(pos % rb);
  size_t first = std::min(n, (size_t)rb - off);
  std::memcpy(dst, base + off, first);
  if (n > first) std::memcpy(dst + first, base, n - first);
}

}  // namespace stshm

namespace {

using Clock = std::chrono::steady_clock;

constexpr uint32_t kMaxPayload = 1u << 30;  // 1 GiB sanity cap
// 'STT3' since r06: DATA/BURST payloads gained a u32 tx_seq after the kind
// byte (go-back-N, comm/wire.py). The framing change is handshake-breaking
// by design — a pre-seq peer pairing with a post-seq peer would silently
// mis-ack (old rule: undecodable still counts) or discard-and-churn; the
// magic bump turns both into an explicit join rejection.
constexpr char kMagic[4] = {'S', 'T', 'T', '3'};
// r11 multi-socket link striping. A joiner that wants a striped link
// sends the 'STT4' hello ([magic][u32 hint][u32 want_stripes]); the
// acceptor replies 'Y' + [u8 granted][u64 token] and the joiner opens
// granted-1 extra connections, each announcing itself with the 'STTS'
// stripe hello ([magic][u64 token][u8 stripe_idx], ack 'y'). Per-stripe
// framing gains an 8-byte stripe sequence after the length prefix
// ([u32 len][u64 sseq][payload]; len == 0 keepalives stay 4 bytes), from
// which the receiver reassembles the link's single in-order stream —
// round-robin striping with per-message tags, so any stripe may carry any
// message and a dead stripe's in-flight messages re-route to survivors.
// stripe_count == 1 keeps the STT3 hello and the r10 framing byte-for-
// byte (the compat escape hatch for joining pre-r11 trees); an STT4 hello
// at a pre-r11 acceptor fails the magic check and is rejected, the same
// explicit-breakage discipline as the STT3 bump itself.
constexpr char kMagic4[4] = {'S', 'T', 'T', '4'};
constexpr char kMagicS[4] = {'S', 'T', 'T', 'S'};
constexpr int kMaxStripes = 8;
// Reorder window: how far (in messages) one stripe may run ahead of the
// link's in-order delivery point before its reader blocks — the
// backpressure that bounds reassembly memory (a dead stripe holding the
// window closed is eventually killed by its liveness timeout).
constexpr uint64_t kReorderWindow = 4096;
// Messages coalesced into ONE kernel crossing on the clean send path
// (faults and pacing off): r11 gathered up to 8 into a single writev;
// r14 widens the batch and submits it as one sendmmsg — each queued
// message keeps its own mmsghdr (header + payload iovecs, borrowed ring
// slots included, no copies), so partial completion is handled
// per-message instead of by re-walking one flat iovec window.
constexpr int kCoalesce = 16;

// ---- fault injection (env-gated hook table; comm/faults.py to_env) -------
//
// ST_FAULT_PLAN="seed=N,drop=P,dup=P,trunc=P,corrupt=P,delay_pct=P,
// delay_ms=M,stall_after=K,sever_after=K,only_link=L" installs deterministic
// wire faults on every node CREATED while the variable is set (parsed per
// st_node_create, so a test can make exactly one node chaotic). Faults
// apply only to DATA frames on the sender side — native framing kind 0/7,
// or any non-keepalive payload in wire-compat mode — never to handshake or
// ACK traffic, so injected chaos drives the recovery machinery (ledger
// rollback, carry, re-graft) instead of wedging a join. This is the native
// twin of the Python tier's FaultPlan (comm/faults.py): both tiers face
// the same fault classes from the same config.
//
// ST_FAULT_CRASH="point:N" additionally arms a process-wide kill at a
// named protocol point (here: "mid-join-walk"); see also stengine.cpp's
// points. The process dies with _exit(17) — no destructors, no drain:
// the whole point is that nothing below the point runs.
struct FaultPlan {
  int enabled = 0;
  uint64_t seed = 0;
  double drop = 0, dup = 0, trunc = 0, corrupt = 0, delay_pct = 0;
  double delay_ms = 0;
  int64_t stall_after = -1;  // >=0: swallow data frames past the Nth, per link
  int64_t sever_after = 0;   // >0: hard-kill the link at its Nth data frame
  int32_t only_link = 0;     // >0: restrict ALL faults to this one link id
  // >=0: restrict ALL faults to this stripe index of each (striped) link —
  // the per-stripe chaos arm. sever_after then kills just that stripe
  // (the link degrades to the survivors) instead of the whole link.
  int32_t only_stripe = -1;
};

FaultPlan parse_fault_plan() {
  FaultPlan p;
  const char* env = getenv("ST_FAULT_PLAN");
  if (!env || !*env) return p;
  p.enabled = 1;
  std::string s(env);
  size_t i = 0;
  while (i <= s.size()) {
    size_t j = s.find(',', i);
    if (j == std::string::npos) j = s.size();
    std::string kv = s.substr(i, j - i);
    size_t eq = kv.find('=');
    if (eq != std::string::npos) {
      std::string k = kv.substr(0, eq);
      double v = atof(kv.c_str() + eq + 1);
      if (k == "seed") p.seed = (uint64_t)v;
      else if (k == "drop") p.drop = v;
      else if (k == "dup") p.dup = v;
      else if (k == "trunc") p.trunc = v;
      else if (k == "corrupt") p.corrupt = v;
      else if (k == "delay_pct") p.delay_pct = v;
      else if (k == "delay_ms") p.delay_ms = v;
      else if (k == "stall_after") p.stall_after = (int64_t)v;
      else if (k == "sever_after") p.sever_after = (int64_t)v;
      else if (k == "only_link") p.only_link = (int32_t)v;
      else if (k == "only_stripe") p.only_stripe = (int32_t)v;
    }
    i = j + 1;
  }
  return p;
}

// xorshift64: deterministic per-link stream (seeded seed ^ f(link id)),
// uniform in [0, 1). Never zero-state (the splat constant guards it).
inline double frand64(uint64_t* st) {
  uint64_t x = *st ? *st : 0x9e3779b97f4a7c15ull;
  x ^= x << 13;
  x ^= x >> 7;
  x ^= x << 17;
  *st = x;
  return (double)(x >> 11) / (double)(1ull << 53);
}

struct Config {
  int32_t wire_compat = 0;
  // compat mode: fixed frame payload size (4 + ceil(n/8)); native: 0.
  int32_t compat_frame_bytes = 0;
  int32_t listen_backlog = 128;
  int64_t bandwidth_cap_bps = 0;   // outgoing payload bytes/sec per link
  double peer_timeout_sec = 30.0;  // 0 = no liveness timeout
  double keepalive_sec = 1.0;
  int32_t max_children = 2;
  int32_t queue_depth = 8;
  int32_t max_rejoin_attempts = 8;
  double rejoin_backoff_sec = 0.2;
  // Bounded joins (TransportConfig twins): per-attempt connect()/reply
  // bound and the total create-time join budget. 0 = legacy behavior
  // (blocking connect / fixed attempt count).
  double connect_timeout_sec = 5.0;
  double join_timeout_sec = 30.0;
  int32_t stripe_count = 1;  // sockets per logical link (r11; 1..8)
  FaultPlan fault;  // env-gated wire chaos (parse_fault_plan)
};

struct Event {
  int32_t kind;  // 1 = link up, 2 = link down, 3 = became master
  int32_t link_id;
  int32_t is_uplink;
};

// One outgoing wire message (r07 ring-buffer data plane). Two ownership
// modes:
//  - OWNED: `owned` holds a private copy (the legacy st_node_send path —
//    the bytes cross the ctypes boundary once, into a pooled vector);
//  - BORROWED (zero-copy): `zdata/zlen` point into the CALLER's buffer
//    (the native engine's tx ring slot); the transport guarantees it calls
//    `release(ctx)` exactly once when it is done with the bytes — after
//    the socket write, or at teardown if the link dies with the message
//    still queued. Destruction IS the release (RAII), so no teardown path
//    can leak a ring slot.
// A borrowed message's bytes double as the sender's retransmission ledger
// entry, so the transport must never MUTATE them: the fault injector
// copies-on-write before corrupting (see link_sender_loop).
struct OutMsg {
  std::vector<uint8_t> owned;
  const uint8_t* zdata = nullptr;
  uint32_t zlen = 0;
  void (*release)(void*) = nullptr;
  void* ctx = nullptr;
  // Stripe sequence (r11): stamped at enqueue (push_hook under the queue
  // mutex), written on the wire after the length prefix of striped links,
  // and the receiver's reassembly key. A re-enqueued message (its stripe
  // died at write time) keeps its stamp — the receiver's window dedups if
  // the dead socket had actually delivered it.
  uint64_t sseq = 0;

  OutMsg() = default;
  OutMsg(const OutMsg&) = delete;
  OutMsg& operator=(const OutMsg&) = delete;
  OutMsg(OutMsg&& o) noexcept { *this = std::move(o); }
  OutMsg& operator=(OutMsg&& o) noexcept {
    if (this != &o) {
      reset();
      owned = std::move(o.owned);
      zdata = o.zdata;
      zlen = o.zlen;
      release = o.release;
      ctx = o.ctx;
      sseq = o.sseq;
      o.zdata = nullptr;
      o.zlen = 0;
      o.release = nullptr;
      o.ctx = nullptr;
    }
    return *this;
  }
  void reset() {
    if (release) {
      release(ctx);
      release = nullptr;
    }
    zdata = nullptr;
    zlen = 0;
  }
  ~OutMsg() { reset(); }
  const uint8_t* data() const { return zdata ? zdata : owned.data(); }
  size_t size() const { return zdata ? zlen : owned.size(); }
};

// Bounded MPMC queue with close() wakeup; carries received byte buffers
// (recvq) or OutMsg send descriptors (sendq).
template <typename T>
class FrameQueue {
 public:
  explicit FrameQueue(size_t cap) : cap_(cap) {}

  bool push(T&& f, double timeout_sec) {
    return push_hook(std::move(f), timeout_sec, [](T&) {});
  }

  // push with a stamp hook run under the queue mutex at insertion — the
  // r11 stripe-seq stamp site (a failed/timed-out push runs no hook, so
  // a stamped sequence is always eventually written).
  // Explicit deadline loops (not wait_for-with-predicate) throughout this
  // class: a predicate lambda reads the mu_-guarded queue state from a
  // context the thread-safety analysis treats as lock-free.
  template <typename F>
  bool push_hook(T&& f, double timeout_sec, F&& hook) {
    StUniqueLock lk(mu_);
    const auto deadline = st_cv_deadline(timeout_sec);
    while (!closed_ && q_.size() >= cap_) {
      if (not_full_.wait_until(lk.native(), deadline) ==
          std::cv_status::timeout)
        break;
    }
    if (closed_ || q_.size() >= cap_) return false;
    hook(f);
    q_.push_back(std::move(f));
    not_empty_.notify_one();
    return true;
  }

  bool pop(T* out, double timeout_sec) {
    StUniqueLock lk(mu_);
    const auto deadline = st_cv_deadline(timeout_sec);
    while (!closed_ && q_.empty()) {
      if (not_empty_.wait_until(lk.native(), deadline) ==
          std::cv_status::timeout)
        break;
    }
    if (q_.empty()) return false;  // timed out, or closed and drained
    *out = std::move(q_.front());
    q_.pop_front();
    not_full_.notify_one();
    return true;
  }

  size_t size() {
    StLockGuard lk(mu_);
    return q_.size();
  }

  void close() {
    StLockGuard lk(mu_);
    closed_ = true;
    not_empty_.notify_all();
    not_full_.notify_all();
  }

 private:
  StMutex mu_;
  std::condition_variable not_empty_, not_full_;
  std::deque<T> q_ ST_GUARDED_BY(mu_);
  size_t cap_;
  bool closed_ ST_GUARDED_BY(mu_) = false;
};

// Small free-list of byte buffers (capacity-preserving): the per-message
// heap allocation the r07 data plane removes. Bounded so an idle link's
// high-water mark doesn't pin memory forever.
class BufPool {
 public:
  explicit BufPool(size_t keep) : keep_(keep) {}

  // a recycled buffer (capacity warm) or a fresh one; `hit` reports which
  std::vector<uint8_t> get(bool* hit) {
    StLockGuard lk(mu_);
    if (!free_.empty()) {
      std::vector<uint8_t> b = std::move(free_.back());
      free_.pop_back();
      *hit = true;
      return b;
    }
    *hit = false;
    return {};
  }

  void put(std::vector<uint8_t>&& b) {
    StLockGuard lk(mu_);
    if (free_.size() < keep_) free_.push_back(std::move(b));
    // else: drop — the deallocation is the bound, not a leak
  }

 private:
  StMutex mu_;
  std::vector<std::vector<uint8_t>> free_ ST_GUARDED_BY(mu_);
  size_t keep_;
};

// One full-duplex framed TCP link (the reference's synca/sync_in thread pair,
// src/sharedtensor.c:113-189, minus the codec math which lives on-device).
struct Link {
  int32_t id = -1;
  int fd = -1;  // stripe 0's fd (kept for the pre-stripe call sites)
  int32_t is_uplink = 0;
  std::atomic<bool> alive{true};
  // r11 striping: up to kMaxStripes sockets carry this ONE logical link.
  // stripe_fd[0] == fd; each ATTACHED stripe runs its own sender+receiver
  // thread pair (the last of a stripe's two threads closes that stripe's
  // fd — same fd-reuse rationale as the old io_refs). A stripe dies alone
  // (kill_stripe: messages re-route, receiver reassembly skips nothing
  // because sseq tags survive); the LAST live stripe's death is the
  // link's.
  int nstripes = 1;
  // Atomic: the acceptor's attach_stripe (listener thread, replayed-STTS
  // guard included) stores a stripe's fd while kill_link/kill_stripe and
  // the sibling I/O threads read the array — a plain int here was a
  // narrow but real data race (the fd VALUE is still stable from each
  // reader's perspective: it is written once per attached stripe, and the
  // idx-reuse guard rejects re-attachment).
  std::atomic<int> stripe_fd[kMaxStripes];
  std::atomic<bool> stripe_ok[kMaxStripes] = {};
  std::atomic<int> stripe_io[kMaxStripes] = {};
  std::atomic<int> stripes_live{0};
  std::atomic<uint64_t> stripe_deaths{0}, reroutes{0};
  // tx stripe-seq allocator (stamped in push_hook / dup-injection)
  std::atomic<uint64_t> sseq_next{0};
  // rx reassembly (striped links only): out-of-order messages park in
  // `reorder` until `rnext` arrives; `delivering` elects one drainer; the
  // window condvar blocks readers that run too far ahead (backpressure).
  StMutex rmu;
  std::condition_variable rcv;
  std::map<uint64_t, std::vector<uint8_t>> reorder ST_GUARDED_BY(rmu);
  uint64_t rnext ST_GUARDED_BY(rmu) = 0;
  bool delivering ST_GUARDED_BY(rmu) = false;
  // stripe senders share the per-link fault-plan state below; the mutex
  // is taken ONLY when the plan is enabled (chaos builds)
  StMutex fault_mu;
  FrameQueue<OutMsg> sendq;
  FrameQueue<std::vector<uint8_t>> recvq;
  // r07 buffer recycling: tx buffers cycle enqueue -> socket write -> free
  // list; rx buffers cycle socket read -> recvq -> consumer copy-out
  // (st_node_recv) -> free list. Bounded at queue_depth + 2 each, so the
  // steady state allocates nothing per message without pinning an idle
  // link's high-water memory.
  BufPool tx_pool, rx_pool;
  // stats
  std::atomic<uint64_t> bytes_out{0}, bytes_in{0}, frames_out{0}, frames_in{0};
  // the peer address as observed by accept(); because children bind their
  // listen socket to their uplink's local endpoint (the reference's
  // addressing trick, src/sharedtensor.c:292-316), this doubles as the
  // child's listen address for redirects.
  sockaddr_in peer_addr{};
  // fault-injection state (only touched when the node's plan is enabled;
  // stripe senders share it under fault_mu)
  uint64_t fault_rng ST_GUARDED_BY(fault_mu) = 0;
  // data frames seen at this wire boundary
  int64_t fault_frames ST_GUARDED_BY(fault_mu) = 0;
  // r14 same-host shm lane (stshm::Lane), set ONCE under Node::mu by
  // st_node_shm_serve/join and read lock-free everywhere after (the
  // pointer never changes once non-null; the Lane's own fields are
  // atomics or written before publication). Freed by ~Link, which runs
  // only after every I/O thread dropped its shared_ptr.
  std::atomic<stshm::Lane*> shm{nullptr};

  Link(size_t qdepth)
      : sendq(qdepth),
        recvq(qdepth),
        tx_pool(qdepth + 2),
        rx_pool(qdepth + 2) {
    for (auto& f : stripe_fd) f.store(-1, std::memory_order_relaxed);
  }
  ~Link() { delete shm.load(std::memory_order_acquire); }
};

struct Node;
void link_sender_loop(Node* node, std::shared_ptr<Link> link, int sidx);
void link_receiver_loop(Node* node, std::shared_ptr<Link> link, int sidx);
void shm_rx_loop(Node* node, std::shared_ptr<Link> link);
bool deliver_striped(Node* node, const std::shared_ptr<Link>& link,
                     uint64_t sseq, std::vector<uint8_t>&& frame);
void listener_loop(Node* node, int listen_fd);
void rejoin_loop(Node* node);

struct Node {
  Config cfg;
  // process-unique obs id: tags this node's events on the shared per-thread
  // rings so a multi-peer process still yields per-node timelines
  uint32_t obs_id = 0;
  std::atomic<bool> closing{false};
  std::atomic<int> active_threads{0};  // all detached; close() drains to 0
  int listen_fd = -1;

  StMutex mu;  // guards membership: links, child slots, next id, role
  // Second listener bound to the rendezvous address after a master
  // failover (rejoin_loop); -1 until then.
  int rendezvous_listen_fd ST_GUARDED_BY(mu) = -1;
  std::map<int32_t, std::shared_ptr<Link>> links ST_GUARDED_BY(mu);
  // up to max_children (<=16)
  std::shared_ptr<Link> child_slot[16] ST_GUARDED_BY(mu);
  int lrcounter ST_GUARDED_BY(mu) = 0;
  int32_t next_link_id ST_GUARDED_BY(mu) = 1;
  int32_t uplink_id ST_GUARDED_BY(mu) = -1;
  // r11: accepted-but-not-yet-attached stripe grants (listener 'STT4'
  // accept -> the joiner's 'STTS' stripe hellos resolve here). Guarded by
  // mu; entries expire after connect_timeout-ish and are pruned lazily.
  struct PendingStripe {
    uint64_t token;
    std::shared_ptr<Link> link;
    Clock::time_point deadline;
  };
  std::vector<PendingStripe> pending_stripes ST_GUARDED_BY(mu);
  uint64_t token_rng ST_GUARDED_BY(mu) = 0;  // seeded at create

  StMutex ev_mu;
  std::deque<Event> events ST_GUARDED_BY(ev_mu);
  std::condition_variable ev_cv;

  // Data-arrival signal: bumped (and notified) whenever any link pushes a
  // received frame, so a consumer (the native engine's receiver) can BLOCK
  // for new input across all links instead of polling each queue — the
  // poll-interval latency floor the Python tier suffers from (50ms drain /
  // 2ms recv sleeps) has no reason to exist at this layer.
  StMutex data_mu;
  std::condition_variable data_cv;
  uint64_t data_seq ST_GUARDED_BY(data_mu) = 0;

  sockaddr_in rendezvous{};  // written once at create, before any thread
  bool is_master ST_GUARDED_BY(mu) = false;
  std::string last_error;  // create-time only (no thread yet)
  uint64_t jrng = 0;  // rejoin-backoff jitter stream (rejoin_loop only;
                      // create seeds it before the thread starts)

  // r07 pool observability (st_node_pool_stats): steady state must show
  // acquires growing while misses (fresh allocations) stay flat — the
  // zero-per-message-allocation assertion the tests/metrics make.
  std::atomic<uint64_t> tx_acquires{0}, tx_pool_misses{0};
  std::atomic<uint64_t> rx_acquires{0}, rx_pool_misses{0};
  std::atomic<uint64_t> zc_msgs{0};  // zero-copy (borrowed) sends enqueued

  // r14 zero-copy receive loans (st_node_recv_zc): the popped rx buffer
  // parks here, keyed by link id, until the NEXT recv_zc/recv_done on the
  // same link releases it — so the borrowed pointer stays valid even if
  // the Link itself is torn down mid-parse. Loans live on the NODE (not
  // the Link) precisely for that teardown window. loan_mu is a leaf
  // (nothing acquired under it); it is taken sequentially with mu, never
  // nested.
  StMutex loan_mu;
  std::map<int32_t, std::vector<uint8_t>> loans ST_GUARDED_BY(loan_mu);

  void notify_data() ST_EXCLUDES(data_mu) {
    {
      StLockGuard lk(data_mu);
      data_seq++;
    }
    data_cv.notify_all();
  }

  void emit(int32_t kind, int32_t link_id, int32_t is_uplink)
      ST_EXCLUDES(ev_mu) {
    // membership events double as timeline events (codes 1..4 == kinds)
    st_obs_emit(obs_id, (uint32_t)kind, link_id, (uint64_t)is_uplink);
    StLockGuard lk(ev_mu);
    events.push_back({kind, link_id, is_uplink});
    ev_cv.notify_all();
  }
};

// ---- robust I/O (the reference's read_or_die/write_or_die, but returning
// errors instead of exiting the process) --------------------------------

bool read_full(int fd, uint8_t* buf, size_t count) {
  while (count) {
    ssize_t r = ::read(fd, buf, count);
    if (r == 0) return false;  // peer closed
    if (r < 0) {
      if (errno == EINTR) continue;
      return false;  // includes EAGAIN from SO_RCVTIMEO => liveness timeout
    }
    buf += r;
    count -= r;
  }
  return true;
}

bool write_full(int fd, const uint8_t* buf, size_t count) {
  while (count) {
    ssize_t r = ::write(fd, buf, count);
    if (r < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    buf += r;
    count -= r;
  }
  return true;
}

// Scatter-gather write: length-prefix + payload leave in ONE syscall
// (writev) instead of the old two write()s per message — and the payload
// iovec can point straight into a borrowed ring slot (no contiguous
// hdr+payload buffer ever exists). Handles short writes by advancing the
// iovec window.
bool writev_full(int fd, struct iovec* iov, int iovcnt) {
  while (iovcnt > 0 && iov->iov_len == 0) {
    iov++;
    iovcnt--;
  }
  while (iovcnt > 0) {
    ssize_t r = ::writev(fd, iov, iovcnt);
    if (r < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    size_t n = (size_t)r;
    while (iovcnt > 0 && n >= iov->iov_len) {
      n -= iov->iov_len;
      iov++;
      iovcnt--;
    }
    if (iovcnt > 0) {
      iov->iov_base = (uint8_t*)iov->iov_base + n;
      iov->iov_len -= n;
    }
  }
  return true;
}

inline bool listen_fd_ok(int fd) { return fd >= 0; }

void set_common_sockopts(int fd) {
  int yes = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &yes, sizeof yes);
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &yes, sizeof yes);
}

void set_recv_timeout(int fd, double sec) {
  if (sec <= 0) return;
  timeval tv;
  tv.tv_sec = (time_t)sec;
  tv.tv_usec = (suseconds_t)((sec - (double)tv.tv_sec) * 1e6);
  setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof tv);
}

// Bounded connect: nonblocking connect + poll, restoring blocking mode on
// the way out. The reference's blocking connect() hangs FOREVER against a
// rendezvous that drops packets (no RST) — the join walk needs a per-hop
// bound so a dead target fails in bounded time instead (ISSUE r06
// tentpole). timeout <= 0 keeps the legacy blocking behavior.
bool connect_with_timeout(int fd, const sockaddr_in* addr,
                          double timeout_sec) {
  if (timeout_sec <= 0)
    return ::connect(fd, (const sockaddr*)addr, sizeof *addr) == 0;
  int flags = fcntl(fd, F_GETFL, 0);
  fcntl(fd, F_SETFL, flags | O_NONBLOCK);
  int r = ::connect(fd, (const sockaddr*)addr, sizeof *addr);
  bool ok = r == 0;
  if (!ok && errno == EINPROGRESS) {
    pollfd pfd{};
    pfd.fd = fd;
    pfd.events = POLLOUT;
    if (::poll(&pfd, 1, (int)(timeout_sec * 1000.0)) == 1) {
      int err = 0;
      socklen_t len = sizeof err;
      getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &len);
      ok = err == 0;
    }
  }
  fcntl(fd, F_SETFL, flags);
  return ok;
}

// ---- link lifecycle ------------------------------------------------------

// Spawn the I/O thread pair for one ATTACHED stripe (stripe 0 at
// make_link; extra stripes as their sockets arrive — joiner's
// open_stripes / acceptor's 'STTS' hello).
void attach_stripe(Node* node, const std::shared_ptr<Link>& link, int sidx,
                   int fd) {
  link->stripe_fd[sidx] = fd;
  link->stripe_io[sidx].store(2);
  link->stripe_ok[sidx].store(true);
  link->stripes_live++;
  set_recv_timeout(fd, node->cfg.peer_timeout_sec);
  node->active_threads += 2;
  std::thread(link_sender_loop, node, link, sidx).detach();
  std::thread(link_receiver_loop, node, link, sidx).detach();
}

std::shared_ptr<Link> make_link(Node* node, int fd, int32_t is_uplink,
                                const sockaddr_in* peer, int nstripes = 1) {
  auto link = std::make_shared<Link>((size_t)node->cfg.queue_depth);
  if (nstripes < 1) nstripes = 1;
  if (nstripes > kMaxStripes) nstripes = kMaxStripes;
  {
    StLockGuard lk(node->mu);
    link->id = node->next_link_id++;
    link->fd = fd;
    link->nstripes = nstripes;
    link->is_uplink = is_uplink;
    if (peer) link->peer_addr = *peer;
    node->links[link->id] = link;
    if (is_uplink) node->uplink_id = link->id;
  }
  attach_stripe(node, link, 0, fd);
  node->emit(1, link->id, is_uplink);
  return link;
}

// Tear down one link (all stripes); the rest of the node keeps running
// (the fix for the reference's exit(-1)-on-any-error model,
// src/sharedtensor.c:61-63).
void kill_link(Node* node, std::shared_ptr<Link> link) {
  bool was_alive = link->alive.exchange(false);
  if (!was_alive) return;
  for (int i = 0; i < link->nstripes; i++)
    if (link->stripe_fd[i] >= 0) ::shutdown(link->stripe_fd[i], SHUT_RDWR);
  // shm lane down with the link: mark the segment closed and futex-wake
  // both rings so a blocked peer writer/reader (and our own shm threads)
  // observe the death instead of sleeping out their timeout slices
  if (stshm::Lane* sl = link->shm.load(std::memory_order_acquire))
    sl->close_and_wake();
  link->sendq.close();
  link->recvq.close();
  {
    StLockGuard lk(link->rmu);
  }
  link->rcv.notify_all();  // unblock window-waiting stripe readers
  bool was_uplink = false;
  {
    StLockGuard lk(node->mu);
    for (int i = 0; i < node->cfg.max_children; i++)
      if (node->child_slot[i] == link) node->child_slot[i] = nullptr;
    if (node->uplink_id == link->id) {
      node->uplink_id = -1;
      was_uplink = true;
    }
    node->links.erase(link->id);
  }
  node->emit(2, link->id, was_uplink ? 1 : 0);
  // fds are closed by each stripe's last I/O thread (stripe_io_exit);
  // shutdown() above already unblocked them all.
}

// Tear down ONE stripe; the link degrades to the survivors (in-flight
// messages re-route by stripe-seq), and the LAST stripe's death is the
// link's.
void kill_stripe(Node* node, std::shared_ptr<Link> link, int sidx) {
  bool was = link->stripe_ok[sidx].exchange(false);
  if (!was) return;
  ::shutdown(link->stripe_fd[sidx], SHUT_RDWR);
  link->rcv.notify_all();
  if (--link->stripes_live <= 0) {
    // the LAST stripe's death is the link's (link_down event), and an
    // unstriped link's only teardown path runs through here too —
    // neither is a degradation, so neither counts a stripe death
    kill_link(node, link);
    return;
  }
  link->stripe_deaths++;
  st_obs_emit(node->obs_id, stobs::kEvStripeDown, link->id, (uint64_t)sidx);
}

// Called at the end of each detached stripe-I/O thread.
void stripe_io_exit(Node* node, const std::shared_ptr<Link>& link,
                    int sidx) {
  if (--link->stripe_io[sidx] == 0) ::close(link->stripe_fd[sidx]);
  --node->active_threads;
}

// Re-enqueue a message whose stripe died before (or during) its write: a
// surviving stripe picks it up, same stripe-seq — the receiver's window
// dedups if the dead socket had in fact delivered it. Dropped (released
// by the destructor) only if the whole link is gone.
void requeue_msg(Node* node, const std::shared_ptr<Link>& link,
                 OutMsg&& m) {
  link->reroutes++;
  while (link->alive && !node->closing) {
    if (link->sendq.push(std::move(m), 0.1)) return;
  }
}

// ---- r14 shm lane I/O ----------------------------------------------------

// Write one [u32 len][u64 sseq][payload] record into the link's shm tx
// ring, streaming payload chunks as the reader frees space (a message
// larger than the ring flows through it). While blocked on a full ring,
// keepalives are injected on the TCP control socket so the lane's
// backpressure never reads as link silence at the peer's liveness timer.
// Returns false when the link/segment died mid-write.
bool shm_write_record(Node* node, const std::shared_ptr<Link>& link,
                      stshm::Lane* sl, int fd, uint64_t sseq,
                      const uint8_t* payload, size_t len)
    ST_EXCLUDES(sl->tx_mu) {
  StLockGuard wlk(sl->tx_mu);  // writer-promotion window (Lane::tx_mu)
  stshm::RingCtl& rc = sl->tx_ctl();
  uint8_t* base = sl->tx_data();
  const uint32_t rb = sl->ring_bytes;
  uint64_t head = rc.head.load(std::memory_order_relaxed);
  auto last_ka = Clock::now();

  auto push_bytes = [&](const uint8_t* src, size_t n) -> bool {
    while (n > 0) {
      if (!link->alive || node->closing ||
          sl->hdr->closed.load(std::memory_order_relaxed))
        return false;
      uint64_t tail = rc.tail.load(std::memory_order_acquire);
      size_t free_b = (size_t)rb - (size_t)(head - tail);
      if (free_b == 0) {
        // spin-before-sleep, then a BOUNDED futex nap (teardown works by
        // waking these words, but the bound means a lost wake costs
        // 100 ms, never a hang)
        bool moved = false;
        for (int s = 0; s < stshm::kSpins; s++) {
          if (rc.tail.load(std::memory_order_acquire) != tail) {
            moved = true;
            break;
          }
#if defined(__x86_64__)
          __builtin_ia32_pause();
#endif
        }
        if (!moved) {
          sl->tx_waits.fetch_add(1, std::memory_order_relaxed);
          uint32_t seq = rc.tail_seq.load(std::memory_order_acquire);
          rc.wr_waiting.fetch_add(1, std::memory_order_seq_cst);
          if (rc.tail.load(std::memory_order_acquire) == tail)
            stshm::futex_wait(&rc.tail_seq, seq, 100);
          rc.wr_waiting.fetch_sub(1, std::memory_order_relaxed);
          auto now = Clock::now();
          if (std::chrono::duration<double>(now - last_ka).count() >=
              node->cfg.keepalive_sec) {
            uint8_t z[4] = {0, 0, 0, 0};
            if (!write_full(fd, z, 4)) return false;
            link->bytes_out += 4;
            last_ka = now;
          }
        }
        continue;
      }
      size_t c = std::min(free_b, n);
      stshm::ring_put(base, rb, head, src, c);
      head += c;
      src += c;
      n -= c;
#if defined(__x86_64__) && defined(__SSE2__) && !defined(ST_ANALYZE_NO_SIMD)
      _mm_sfence();  // NT stores must drain before the head publish
#endif
      rc.head.store(head, std::memory_order_release);
      rc.head_seq.fetch_add(1, std::memory_order_seq_cst);
      if (rc.rd_waiting.load(std::memory_order_seq_cst))
        stshm::futex_wake_all(&rc.head_seq);
    }
    return true;
  };

  uint8_t hdr[stshm::kRecHdr];
  uint32_t l32 = (uint32_t)len;
  std::memcpy(hdr, &l32, 4);
  std::memcpy(hdr + 4, &sseq, 8);
  // fast path: the whole record fits the free span — ONE publish (and at
  // most one wake) instead of separate header/payload publishes, so the
  // reader wakes once per record, not once per part
  {
    uint64_t tail = rc.tail.load(std::memory_order_acquire);
    if ((size_t)rb - (size_t)(head - tail) >= stshm::kRecHdr + len) {
      stshm::ring_put(base, rb, head, hdr, stshm::kRecHdr);
      if (len > 0)
        stshm::ring_put(base, rb, head + stshm::kRecHdr, payload, len);
      head += stshm::kRecHdr + len;
#if defined(__x86_64__) && defined(__SSE2__) && !defined(ST_ANALYZE_NO_SIMD)
      _mm_sfence();  // NT stores must drain before the head publish
#endif
      rc.head.store(head, std::memory_order_release);
      rc.head_seq.fetch_add(1, std::memory_order_seq_cst);
      if (rc.rd_waiting.load(std::memory_order_seq_cst))
        stshm::futex_wake_all(&rc.head_seq);
      return true;
    }
  }
  if (!push_bytes(hdr, stshm::kRecHdr)) return false;
  if (len > 0 && !push_bytes(payload, len)) return false;
  return true;
}

// Drain the link's shm rx ring. Records re-enter the EXACT delivery path
// the sockets use — striped links through the sseq reassembly window
// (TCP in-flights and ring records interleave correctly), unstriped
// straight into recvq in ring order, gated on the SWITCH marker
// (Lane::rx_go). Exits — and tears the link down, idempotently — on
// teardown or a corrupt record.
void shm_rx_loop(Node* node, std::shared_ptr<Link> link) {
  stshm::Lane* sl = link->shm.load(std::memory_order_acquire);
  stshm::RingCtl& rc = sl->rx_ctl();
  const uint8_t* base = sl->rx_data();
  const uint32_t rb = sl->ring_bytes;
  uint64_t tail = rc.tail.load(std::memory_order_relaxed);
  const bool striped = link->nstripes > 1;

  // A served lane whose joiner never validates (boot-id collision, map
  // failure — the documented keep-TCP fallback) must not cost a polling
  // thread and a parked segment for the link's lifetime: past this
  // deadline the creator closes the lane (tx can never activate on a
  // closed header — a straggler joiner just stays on TCP too), reclaims
  // the segment name, and this thread exits. 30 s dwarfs any legitimate
  // join handshake.
  const auto orphan_deadline = Clock::now() + std::chrono::seconds(30);
  auto orphan_expired = [&]() -> bool {
    return sl->creator != 0 &&
           sl->hdr->joined.load(std::memory_order_acquire) == 0 &&
           Clock::now() > orphan_deadline;
  };

  auto wait_avail = [&](size_t need) -> bool {
    while (link->alive && !node->closing) {
      if (orphan_expired()) return false;
      uint64_t head = rc.head.load(std::memory_order_acquire);
      if (head - tail >= need) return true;
      if (sl->hdr->closed.load(std::memory_order_relaxed))
        return false;  // checked AFTER head: drain what was published
      bool moved = false;
      for (int s = 0; s < stshm::kSpins; s++) {
        if (rc.head.load(std::memory_order_acquire) != head) {
          moved = true;
          break;
        }
#if defined(__x86_64__)
      __builtin_ia32_pause();
#endif
      }
      if (moved) continue;
      sl->rx_waits.fetch_add(1, std::memory_order_relaxed);
      uint32_t seq = rc.head_seq.load(std::memory_order_acquire);
      rc.rd_waiting.fetch_add(1, std::memory_order_seq_cst);
      if (rc.head.load(std::memory_order_acquire) == head)
        stshm::futex_wait(&rc.head_seq, seq, 100);
      rc.rd_waiting.fetch_sub(1, std::memory_order_relaxed);
    }
    return false;
  };
  auto consume = [&](size_t n) {
    tail += n;
    rc.tail.store(tail, std::memory_order_release);
    rc.tail_seq.fetch_add(1, std::memory_order_seq_cst);
    if (rc.wr_waiting.load(std::memory_order_seq_cst))
      stshm::futex_wake_all(&rc.tail_seq);
  };

  // Optional delivery coalescing (ST_SHM_COALESCE_US, default OFF): hold
  // delivery until a few COMPLETE records are present or the window
  // expires, then deliver back-to-back. Measured on this box it LOSES —
  // the steady state is a closed loop paced by the go-back-N window, so
  // any delivery delay delays ACKs and stalls the producer (65 Ki:
  // 23.3 k f/s at hold 0 vs 19.4 k at 5 ms) — but the lever is the
  // first thing to re-try on a box where consumer-side pass amortization
  // dominates, so it stays env-gated rather than deleted.
  static const uint64_t kHoldNs = [] {
    const char* e = getenv("ST_SHM_COALESCE_US");
    long us = e && *e ? atol(e) : 0;
    if (us < 0) us = 0;
    if (us > 50000) us = 50000;
    return (uint64_t)us * 1000u;
  }();
  constexpr int kHoldMsgs = 4;
  // complete records currently in the ring (capped at kHoldMsgs); walks
  // record headers ahead of `tail` without consuming
  auto complete_records = [&]() -> int {
    uint64_t head = rc.head.load(std::memory_order_acquire);
    uint64_t pos = tail;
    int cnt = 0;
    while (cnt < kHoldMsgs && pos + stshm::kRecHdr <= head) {
      uint8_t lh[4];
      stshm::ring_get(base, rb, pos, lh, 4);
      uint32_t l;
      std::memcpy(&l, lh, 4);
      if (l > kMaxPayload) return cnt + 1;  // corrupt: let delivery red it
      if (pos + stshm::kRecHdr + l > head) break;
      cnt++;
      pos += stshm::kRecHdr + l;
    }
    return cnt;
  };
  // read + deliver ONE record; 0 = delivered, 1 = teardown, 2 = corrupt
  auto deliver_one = [&]() -> int {
    if (!wait_avail(stshm::kRecHdr)) return 1;
    uint8_t h[stshm::kRecHdr];
    stshm::ring_get(base, rb, tail, h, stshm::kRecHdr);
    uint32_t len;
    uint64_t sseq;
    std::memcpy(&len, h, 4);
    std::memcpy(&sseq, h + 4, 8);
    if (len > kMaxPayload) return 2;  // corrupt ring
    consume(stshm::kRecHdr);
    bool hit = false;
    std::vector<uint8_t> frame = link->rx_pool.get(&hit);
    node->rx_acquires++;
    if (!hit) node->rx_pool_misses++;
    frame.resize(len);
    size_t got = 0;
    while (got < len) {
      if (!wait_avail(1)) return 1;  // mid-record teardown
      uint64_t head = rc.head.load(std::memory_order_acquire);
      size_t n = std::min((size_t)(head - tail), len - got);
      stshm::ring_get(base, rb, tail, frame.data() + got, n);
      got += n;
      consume(n);
    }
    link->bytes_in += stshm::kRecHdr + len;
    link->frames_in++;
    sl->msgs_in.fetch_add(1, std::memory_order_relaxed);
    sl->bytes_in.fetch_add(stshm::kRecHdr + len, std::memory_order_relaxed);
    if (striped) {
      if (!deliver_striped(node, link, sseq, std::move(frame))) return 1;
      return 0;
    }
    while (link->alive && !node->closing) {
      if (link->recvq.push(std::move(frame), 0.5)) {
        node->notify_data();
        return 0;
      }
    }
    return 1;
  };

  bool clean = false;
  while (link->alive && !node->closing) {
    if (orphan_expired()) {
      clean = true;  // the LINK stays up on TCP; only the lane dies
      break;
    }
    if (!sl->rx_go.load(std::memory_order_acquire)) {
      // unstriped pre-marker window: records may already sit in the ring;
      // they wait for the marker's in-stream ordering point
      std::this_thread::sleep_for(std::chrono::milliseconds(1));
      continue;
    }
    if (!wait_avail(stshm::kRecHdr)) {
      clean = true;
      break;
    }
    int avail = complete_records();
    if (kHoldNs > 0 && avail >= 1 && avail < kHoldMsgs) {
      uint64_t t0 = stobs::now_ns();
      while (avail < kHoldMsgs && link->alive && !node->closing &&
             !sl->hdr->closed.load(std::memory_order_relaxed) &&
             stobs::now_ns() - t0 < kHoldNs) {
        uint32_t seq = rc.head_seq.load(std::memory_order_acquire);
        uint64_t h0 = rc.head.load(std::memory_order_acquire);
        rc.rd_waiting.fetch_add(1, std::memory_order_seq_cst);
        if (rc.head.load(std::memory_order_acquire) == h0)
          stshm::futex_wait(&rc.head_seq, seq, 1);
        rc.rd_waiting.fetch_sub(1, std::memory_order_relaxed);
        avail = complete_records();
      }
    }
    if (avail < 1) avail = 1;  // first record still streaming: deliver now
    int rcod = 0;
    for (int r = 0; r < avail && rcod == 0; r++) rcod = deliver_one();
    if (rcod == 1) {
      clean = true;
      break;
    }
    if (rcod == 2) break;  // corrupt ring: kill the link below
  }
  if (orphan_expired()) {
    // never joined: close the lane (tx can then never activate on
    // either side) and reclaim the segment name now, not at link death
    sl->close_and_wake();
    if (!sl->name.empty()) {
      std::string p = "/dev/shm/" + sl->name;
      ::unlink(p.c_str());  // ~Lane's retry sees ENOENT, harmless
    }
  }
  if (!clean && link->alive && !node->closing) {
    // corrupt record length: the lane is unusable — tear the whole link
    // down (idempotent) so go-back-N recovers on a fresh link
    kill_link(node, link);
  }
  node->notify_data();  // wake blocked consumers to observe any death
  --node->active_threads;
}

// Submit nm stream messages with as few sendmmsg calls as possible. On a
// blocking socket each sendmsg completes fully except when interrupted by
// a signal mid-copy — the sender threads block ALL signals precisely so
// that cannot happen; the last completed message still gets a
// finish-the-remainder writev as belt-and-braces, and a short write on
// any EARLIER message of a batch (impossible with signals blocked) is a
// sheared stream — fail the link rather than continue it.
bool sendmmsg_full(int fd, struct mmsghdr* mm, int nm) {
  int done = 0;
  while (done < nm) {
    int r = ::sendmmsg(fd, mm + done, (unsigned)(nm - done), 0);
    if (r < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    if (r == 0) return false;
    for (int i = done; i < done + r; i++) {
      struct msghdr* mh = &mm[i].msg_hdr;
      size_t total = 0;
      for (size_t v = 0; v < mh->msg_iovlen; v++)
        total += mh->msg_iov[v].iov_len;
      size_t sent = mm[i].msg_len;
      if (sent == total) continue;
      if (i != done + r - 1) return false;  // sheared mid-batch: kill link
      struct iovec* iov = mh->msg_iov;
      int cnt = (int)mh->msg_iovlen;
      size_t n = sent;
      while (cnt > 0 && n >= iov->iov_len) {
        n -= iov->iov_len;
        iov++;
        cnt--;
      }
      if (cnt > 0) {
        iov->iov_base = (uint8_t*)iov->iov_base + n;
        iov->iov_len -= n;
        if (!writev_full(fd, iov, cnt)) return false;
      }
    }
    done += r;
  }
  return true;
}

void link_sender_loop(Node* node, std::shared_ptr<Link> link, int sidx) {
  const bool striped = link->nstripes > 1;
  const int fd = link->stripe_fd[sidx];
  // token bucket for the bandwidth cap (reference README.md:31 TODO);
  // striped links split the budget evenly across stripe senders
  double tokens = 0;
  auto last = Clock::now();
  const int64_t cap =
      node->cfg.bandwidth_cap_bps / (striped ? link->nstripes : 1);
  const FaultPlan& fp = node->cfg.fault;

  // sendmmsg shear guard (see sendmmsg_full): a signal landing mid-sendmsg
  // could short-write one message of a batch; these detached I/O threads
  // never run Python signal handlers anyway (CPython delivers to the main
  // thread), so block everything here.
  {
    sigset_t all;
    sigfillset(&all);
    pthread_sigmask(SIG_BLOCK, &all, nullptr);
  }
  OutMsg msg;
  while (link->alive && link->stripe_ok[sidx].load() && !node->closing) {
    // r14 shm lane: once live, the lane's single writer is the
    // lowest-index LIVE stripe's sender (promotes on stripe death;
    // Lane::tx_mu covers the brief overlap); every other stripe sender
    // stops popping data and only keeps its socket's liveness flowing —
    // TCP stays the control/teardown channel.
    stshm::Lane* sl = node->cfg.wire_compat
                          ? nullptr
                          : link->shm.load(std::memory_order_acquire);
    const bool shm_tx = sl != nullptr && sl->tx_ready();
    if (shm_tx) {
      int wr = 0;
      while (wr < link->nstripes && !link->stripe_ok[wr].load()) wr++;
      if (wr != sidx) {
        // short-sliced idle so a writer-stripe death PROMOTES promptly
        // (one uninterruptible keepalive_sec nap here froze the data
        // plane for up to ~1 s per writer death); the keepalive itself
        // still flows at keepalive cadence
        auto ka_deadline =
            Clock::now() +
            std::chrono::duration<double>(node->cfg.keepalive_sec);
        bool promoted = false;
        while (Clock::now() < ka_deadline) {
          std::this_thread::sleep_for(std::chrono::milliseconds(20));
          if (!link->alive || node->closing ||
              !link->stripe_ok[sidx].load())
            break;
          int w2 = 0;
          while (w2 < link->nstripes && !link->stripe_ok[w2].load()) w2++;
          if (w2 == sidx) {
            promoted = true;  // the writer role fell to us: resume popping
            break;
          }
        }
        if (!link->alive || node->closing || !link->stripe_ok[sidx].load())
          break;
        if (promoted) continue;
        uint8_t z[4] = {0, 0, 0, 0};
        if (!write_full(fd, z, 4)) break;
        link->bytes_out += 4;
        continue;
      }
    }
    bool have = link->sendq.pop(&msg, node->cfg.keepalive_sec);
    if (!link->alive || node->closing) break;
    if (!link->stripe_ok[sidx].load()) {
      if (have && striped) requeue_msg(node, link, std::move(msg));
      break;
    }
    if (!have) {
      // idle: emit liveness traffic on THIS stripe. Native: zero-length
      // keepalive frame (4 bytes, never a stripe seq). Compat: a
      // zero-scale codec frame — the reference's own idle behavior
      // (quirk Q2), which its peers expect.
      msg.reset();
      bool kok;
      if (node->cfg.wire_compat) {
        bool hit;
        msg.owned = link->tx_pool.get(&hit);
        msg.owned.assign((size_t)node->cfg.compat_frame_bytes, 0);
        kok = write_full(fd, msg.owned.data(), msg.owned.size());
        link->bytes_out += msg.owned.size();
        if (msg.owned.capacity()) {
          link->tx_pool.put(std::move(msg.owned));
          msg.owned = std::vector<uint8_t>();
        }
      } else {
        uint8_t z[4] = {0, 0, 0, 0};
        kok = write_full(fd, z, 4);
        link->bytes_out += 4;
      }
      if (!kok) break;
      continue;
    }
    // ---- fault injection at the wire boundary (Config::fault; the
    // Python tier injects the identical classes in peer._send_blocking).
    // Data frames only: native kind 0/7/11 (incl. the r11 0x80 precision
    // bit), or any queued payload in compat mode. Keepalives are
    // liveness, not data — chaos never silences liveness. Stripe senders
    // share the per-link schedule state under fault_mu (plan-enabled
    // builds only); only_stripe >= 0 confines every class to that stripe.
    size_t write_len = msg.size();
    int write_reps = 1;
    if (fp.enabled) {
      const uint8_t* d = msg.data();
      uint8_t kind0 = msg.size() > 0 ? (uint8_t)(d[0] & 0x7F) : 0xFF;
      // data kinds the chaos classes cover: DATA, BURST, RDATA, and the
      // r16 owner-routed FWD (17) — the sharded tree's whole data plane
      // rides FWD frames, so leaving it out would silently exempt every
      // sharded cluster from wire chaos (tools/lint_wire.py pins this
      // literal set against wire.py's data kinds)
      bool is_data = node->cfg.wire_compat ||
                     (msg.size() > 0 &&
                      (kind0 == 0 || kind0 == 7 || kind0 == 11 ||
                       kind0 == 17));
      if (is_data && (fp.only_link <= 0 || link->id == fp.only_link) &&
          (fp.only_stripe < 0 || sidx == fp.only_stripe)) {
        StUniqueLock flk(link->fault_mu);
        if (!link->fault_rng)
          link->fault_rng =
              (fp.seed + 1) * 0x9e3779b97f4a7c15ull + (uint64_t)link->id;
        int64_t nf = ++link->fault_frames;
        uint64_t* rng = &link->fault_rng;
        if (fp.sever_after > 0 && nf >= fp.sever_after) {
          st_obs_emit(node->obs_id, stobs::kEvFaultSever, link->id,
                      (uint64_t)nf);
          flk.unlock();
          if (striped && fp.only_stripe >= 0) {
            // per-stripe sever: THIS socket dies, the link degrades to
            // the surviving stripes; the in-hand message re-routes.
            // Kill the stripe FIRST: if this was the LAST stripe, the
            // link dies and requeue_msg drops instead of spinning on a
            // full sendq no surviving sender will ever drain.
            kill_stripe(node, link, sidx);
            requeue_msg(node, link, std::move(msg));
            break;
          }
          kill_link(node, link);
          break;
        }
        if (fp.stall_after >= 0 && nf > fp.stall_after) {
          // swallowed: sender layers believe it was delivered (a borrowed
          // slot is still released — via msg's reuse/destruction). On a
          // striped link the swallowed stripe seq additionally wedges
          // reassembly, so the link presents as a black hole until the
          // engine's go-back-N tears it down — the stall contract.
          st_obs_emit(node->obs_id, stobs::kEvFaultStall, link->id,
                      (uint64_t)nf);
          msg.reset();
          continue;
        }
        if (fp.delay_pct > 0 && frand64(rng) < fp.delay_pct) {
          st_obs_emit(node->obs_id, stobs::kEvFaultDelay, link->id,
                      (uint64_t)fp.delay_ms);
          flk.unlock();
          std::this_thread::sleep_for(
              std::chrono::duration<double>(fp.delay_ms / 1000.0));
          flk.lock();
        }
        if (fp.drop > 0 && frand64(rng) < fp.drop) {
          st_obs_emit(node->obs_id, stobs::kEvFaultDrop, link->id,
                      (uint64_t)nf);
          if (!striped) {
            msg.reset();
            continue;
          }
          // striped links must not leave a HOLE in the stripe-seq space
          // (reassembly would wedge the whole link on one injected drop):
          // a dropped message goes out as a 1-byte runt instead — the
          // receiver's decode rejects it without consuming the ENGINE
          // seq, so recovery is the same go-back-N retransmission as a
          // true drop.
          write_len = 1;
        }
        if (fp.corrupt > 0 && msg.size() > 1 && write_len > 1 &&
            frand64(rng) < fp.corrupt) {
          // flip one bit past the kind byte: lands in scales/words, the
          // receiver's decode-guard trust boundary. COPY-ON-WRITE for a
          // borrowed (zero-copy) payload: its bytes ARE the engine's
          // retransmission ledger entry, which must stay byte-identical —
          // corrupting in place would poison every future retransmit of
          // the same message (and the rollback math).
          if (msg.zdata) {
            msg.owned.assign(msg.zdata, msg.zdata + msg.zlen);
            msg.zdata = nullptr;  // release still fires at reset()
            msg.zlen = 0;
          }
          size_t i = 1 + (size_t)(frand64(rng) * (msg.owned.size() - 1));
          if (i >= msg.owned.size()) i = msg.owned.size() - 1;
          msg.owned[i] ^= (uint8_t)(1u << (int)(frand64(rng) * 8));
          st_obs_emit(node->obs_id, stobs::kEvFaultCorrupt, link->id,
                      (uint64_t)i);
        }
        if (fp.trunc > 0 && !node->cfg.wire_compat && msg.size() > 2 &&
            write_len == msg.size() && frand64(rng) < fp.trunc) {
          // well-framed SHORT message (header announces the truncated
          // length): the receiver decodes, rejects, and ACKs it —
          // bounded per-frame loss, not a stream shear. Compat framing
          // is fixed-size, so truncation there would desync every later
          // frame; disabled.
          write_len = 1 + (size_t)(frand64(rng) * (msg.size() - 1));
          if (write_len > msg.size()) write_len = msg.size();
          st_obs_emit(node->obs_id, stobs::kEvFaultTruncate, link->id,
                      (uint64_t)write_len);
        }
        // dup gated off compat like trunc: the reference protocol has no
        // seq dedup, so a duplicated compat frame would double-apply with
        // no recovery path (comm/faults.py FaultPlan.wire_compat)
        if (fp.dup > 0 && !node->cfg.wire_compat &&
            frand64(rng) < fp.dup) {
          write_reps = 2;
          st_obs_emit(node->obs_id, stobs::kEvFaultDup, link->id,
                      (uint64_t)nf);
        }
      }
    }
    if (cap > 0 && msg.size() > 0) {
      auto now = Clock::now();
      tokens += std::chrono::duration<double>(now - last).count() * (double)cap;
      // burst allowance: 100ms worth, so the cap is honored even for the
      // first frames after an idle period
      if (tokens > 0.1 * (double)cap) tokens = 0.1 * (double)cap;
      last = now;
      if ((double)msg.size() > tokens) {
        double wait = ((double)msg.size() - tokens) / (double)cap;
        std::this_thread::sleep_for(std::chrono::duration<double>(wait));
        tokens = 0;
        last = Clock::now();  // the slept interval is spent, not re-credited
      } else {
        tokens -= (double)msg.size();
      }
    }
    // ---- r14 shm lane send path: the message's bytes go straight from
    // the borrowed tx slot (or owned buffer) into the ring record — the
    // zero-copy TxSlot handoff into shm; the fault injector above already
    // ran PER MESSAGE (runt/corrupt/dup/stall/sever), exactly as on the
    // TCP lanes, so chaos coverage is lane-independent.
    if (shm_tx) {
      if (!striped && !sl->marker_sent.exchange(true)) {
        // SWITCH marker: the last data-plane byte this link sends on TCP
        // — the receiver enables ring delivery at exactly this point in
        // the stream (striped links need no marker: ring records carry
        // stripe seqs into the same reassembly window as the sockets)
        uint8_t mk[4];
        uint32_t ml = stshm::kShmSwitchLen;
        std::memcpy(mk, &ml, 4);
        if (!write_full(fd, mk, 4)) break;
        link->bytes_out += 4;
      }
      if (!sl->ev_emitted.exchange(true))
        st_obs_emit(node->obs_id, stobs::kEvShmLaneUp, link->id,
                    (uint64_t)sl->ring_bytes);
      bool sok = true;
      for (int rep = 0; rep < write_reps && sok; rep++) {
        uint64_t sq = msg.sseq;
        size_t wl = write_len;
        if (rep > 0) {
          // injected duplicate: a NEW transport message (fresh stripe
          // seq) carrying the same engine payload, like the TCP path
          sq = link->sseq_next.fetch_add(1, std::memory_order_relaxed);
          wl = msg.size();
        }
        sok = shm_write_record(node, link, sl, fd, sq, msg.data(), wl);
        if (sok) {
          sl->msgs_out.fetch_add(1, std::memory_order_relaxed);
          sl->bytes_out.fetch_add(stshm::kRecHdr + wl,
                                  std::memory_order_relaxed);
        }
      }
      if (!sok) break;  // lane/link died mid-write: normal teardown path
      link->frames_out += 1;
      link->bytes_out += msg.size() + stshm::kRecHdr;
      if (msg.release) {
        msg.reset();
      } else if (msg.owned.capacity()) {
        link->tx_pool.put(std::move(msg.owned));
        msg.owned = std::vector<uint8_t>();
      }
      continue;
    }
    // ---- batched submission (r11 writev -> r14 sendmmsg): on the clean
    // native path (no fault plan, no pacing) opportunistically gather
    // more queued messages and put the whole batch through ONE kernel
    // crossing — each message keeps its own mmsghdr (length prefix,
    // stripe seq and payload iovecs; borrowed ring slots gather without
    // copies), so the syscall/wakeup cost amortizes across the batch and
    // partial completion stays per-message (sendmmsg_full).
    OutMsg batch[kCoalesce];
    int nb = 1;
    batch[0] = std::move(msg);
    if (!node->cfg.wire_compat && !fp.enabled && cap <= 0) {
      while (nb < kCoalesce && link->sendq.pop(&batch[nb], 0.0)) nb++;
    }
    bool ok = true;
    if (node->cfg.wire_compat) {
      for (int rep = 0; rep < write_reps && ok; rep++)
        ok = write_full(fd, batch[0].data(), write_len);
    } else {
      // striped framing: [u32 len][u64 sseq][payload]; legacy: [len][..]
      uint8_t hdrs[2 * kCoalesce][12];
      struct iovec iov[4 * kCoalesce];
      struct mmsghdr mm[2 * kCoalesce];
      std::memset(mm, 0, sizeof mm);
      int niov = 0, nh = 0, nm = 0;
      for (int rep = 0; rep < write_reps; rep++) {
        for (int i = 0; i < nb; i++) {
          size_t wl = i == 0 ? write_len : batch[i].size();
          uint64_t sq = batch[i].sseq;
          if (rep > 0) {
            // an injected duplicate is a NEW transport message (fresh
            // stripe seq) carrying the same engine payload — the
            // engine-level dedup is what the fault exercises, and the
            // stripe window must not swallow it first
            sq = link->sseq_next.fetch_add(1, std::memory_order_relaxed);
          }
          uint8_t* H = hdrs[nh++];
          uint32_t len = (uint32_t)wl;
          std::memcpy(H, &len, 4);
          size_t hlen = 4;
          if (striped) {
            std::memcpy(H + 4, &sq, 8);
            hlen = 12;
          }
          int first = niov;
          iov[niov].iov_base = H;
          iov[niov].iov_len = hlen;
          niov++;
          if (wl) {
            iov[niov].iov_base = (void*)batch[i].data();
            iov[niov].iov_len = wl;
            niov++;
          }
          mm[nm].msg_hdr.msg_iov = &iov[first];
          mm[nm].msg_hdr.msg_iovlen = (size_t)(niov - first);
          nm++;
        }
      }
      ok = sendmmsg_full(fd, mm, nm);
    }
    if (ok) {
      for (int i = 0; i < nb; i++) {
        // compat: one queued payload may carry K concatenated fixed-size
        // frames (the engine's compat bursts) — count the frames actually
        // put on the wire, so sender wire counts reconcile with both the
        // receiver's per-frame re-framing and the engine's per-frame
        // delivery counters (peer.metrics() taxonomy).
        link->frames_out +=
            node->cfg.wire_compat
                ? batch[i].size() / (size_t)node->cfg.compat_frame_bytes
                : 1;
        link->bytes_out += batch[i].size() +
                           (node->cfg.wire_compat ? 0 : (striped ? 12 : 4));
        // recycle: borrowed slots go back to their ring (reset ->
        // release); owned buffers to the link's tx free-list
        if (batch[i].release) {
          batch[i].reset();
        } else if (batch[i].owned.capacity()) {
          link->tx_pool.put(std::move(batch[i].owned));
          batch[i].owned = std::vector<uint8_t>();
        }
      }
    } else {
      if (striped) {
        // the socket died mid-batch: every message in hand re-routes to
        // the surviving stripes (delivery-uncertain ones dedup at the
        // receiver's reassembly window). Kill the stripe BEFORE
        // requeueing: if this was the LAST stripe the link dies with it
        // and requeue_msg drops the batch instead of livelocking on a
        // full sendq that no surviving sender thread will ever drain
        // (go-back-N re-delivers after the re-graft either way).
        kill_stripe(node, link, sidx);
        for (int i = 0; i < nb; i++)
          requeue_msg(node, link, std::move(batch[i]));
      }
      break;
    }
  }
  // a message popped (or half-processed) when the stripe died is released
  // by msg's/batch's destructors (or re-routed above); messages still
  // queued are released when the Link — and with it the sendq deque — is
  // destroyed after every I/O thread exits
  kill_stripe(node, link, sidx);
  stripe_io_exit(node, link, sidx);
}

// Deliver one striped message into the link's in-order stream: park it in
// the reorder map, then drain the consecutive run into recvq (one elected
// drainer at a time — `delivering`). Returns false when the link must die
// (queue closed under us).
bool deliver_striped(Node* node, const std::shared_ptr<Link>& link,
                     uint64_t sseq, std::vector<uint8_t>&& frame) {
  StUniqueLock lk(link->rmu);
  // window backpressure: a stripe that runs too far ahead of the in-order
  // point blocks here (bounding reassembly memory) until delivery
  // advances — or its own liveness timeout kills it if rnext's stripe is
  // truly dead
  while (link->alive && !node->closing &&
         sseq > link->rnext + kReorderWindow) {
    link->rcv.wait_until(lk.native(), st_cv_deadline(0.1));
  }
  if (!link->alive || node->closing) return false;
  if (sseq < link->rnext || link->reorder.count(sseq)) {
    // duplicate of an already-delivered/parked message (a re-routed
    // write whose first copy did land): drop, recycle the buffer
    link->rx_pool.put(std::move(frame));
    return true;
  }
  link->reorder.emplace(sseq, std::move(frame));
  if (link->delivering) return true;
  link->delivering = true;
  while (!link->reorder.empty()) {
    auto it = link->reorder.begin();
    if (it->first < link->rnext) {
      // a re-routed duplicate of the message the drainer had in flight
      // (sseq == rnext while the lock was dropped for the recvq push, so
      // the dedup check above missed it): already delivered — drop it,
      // or this stale head blocks the == rnext test below forever
      link->rx_pool.put(std::move(it->second));
      link->reorder.erase(it);
      continue;
    }
    if (it->first != link->rnext) break;
    std::vector<uint8_t> f = std::move(it->second);
    link->reorder.erase(it);
    lk.unlock();
    bool pushed = false;
    while (link->alive && !node->closing) {
      if (link->recvq.push(std::move(f), 0.5)) {
        node->notify_data();
        pushed = true;
        break;
      }
    }
    lk.lock();
    if (!pushed) {
      link->delivering = false;
      return false;
    }
    link->rnext++;
    link->rcv.notify_all();  // window waiters may proceed
  }
  link->delivering = false;
  return true;
}

void link_receiver_loop(Node* node, std::shared_ptr<Link> link, int sidx) {
  const bool striped = link->nstripes > 1;
  const int fd = link->stripe_fd[sidx];
  while (link->alive && link->stripe_ok[sidx].load() && !node->closing) {
    // decode-side pool (r07): recycle rx buffers through the free list so
    // the steady state reads into warm, already-sized memory — the old
    // fresh-vector-per-message path paid an allocation plus page faults
    // per message (16+ MiB at large-table bursts)
    bool hit = false;
    std::vector<uint8_t> frame = link->rx_pool.get(&hit);
    node->rx_acquires++;
    if (!hit) node->rx_pool_misses++;
    uint64_t sseq = 0;
    if (node->cfg.wire_compat) {
      frame.resize((size_t)node->cfg.compat_frame_bytes);
      if (!read_full(fd, frame.data(), frame.size())) break;
    } else {
      uint8_t hdr[12];
      if (!read_full(fd, hdr, 4)) break;
      uint32_t len = (uint32_t)hdr[0] | ((uint32_t)hdr[1] << 8) |
                     ((uint32_t)hdr[2] << 16) | ((uint32_t)hdr[3] << 24);
      if (len == stshm::kShmSwitchLen) {
        // r14 SWITCH marker (unstriped shm lane): every data message
        // before this point arrived on TCP in order; everything after is
        // in the ring — enable ring delivery at exactly this point. Only
        // ever sent after a successful shm attach, so a pre-r14 peer can
        // never see it.
        if (stshm::Lane* msl = link->shm.load(std::memory_order_acquire))
          msl->rx_go.store(true, std::memory_order_release);
        link->rx_pool.put(std::move(frame));
        continue;
      }
      if (len > kMaxPayload) break;  // protocol violation
      if (len == 0) {                // keepalive (no stripe seq)
        link->rx_pool.put(std::move(frame));
        continue;
      }
      if (striped) {
        if (!read_full(fd, hdr + 4, 8)) break;
        std::memcpy(&sseq, hdr + 4, 8);
      }
      frame.resize(len);
      if (!read_full(fd, frame.data(), len)) break;
    }
    link->bytes_in +=
        frame.size() + (node->cfg.wire_compat ? 0 : (striped ? 12 : 4));
    link->frames_in++;
    if (striped) {
      if (!deliver_striped(node, link, sseq, std::move(frame))) break;
      continue;
    }
    // Block if the consumer is behind: TCP backpressure then paces the
    // peer, exactly like the reference's blocking frame loop. Never drop:
    // frames are cumulative deltas.
    while (link->alive && !node->closing) {
      if (link->recvq.push(std::move(frame), 0.5)) {
        node->notify_data();
        break;
      }
    }
  }
  kill_stripe(node, link, sidx);
  node->notify_data();  // wake blocked consumers so they observe the death
  stripe_io_exit(node, link, sidx);
}

// ---- topology: listener (reference do_listening, src/sharedtensor.c:
// 192-242) ----------------------------------------------------------------

void listener_loop(Node* node, int listen_fd) {
  while (!node->closing) {
    sockaddr_in peer{};
    socklen_t plen = sizeof peer;
    int fd = ::accept(listen_fd, (sockaddr*)&peer, &plen);
    if (fd < 0) {
      if (errno == EINTR) continue;
      if (node->closing) break;
      continue;
    }
    if (node->closing) {
      ::close(fd);
      break;
    }
    set_common_sockopts(fd);

    bool v4 = false;
    int want_stripes = 1;
    if (!node->cfg.wire_compat) {
      // native hello: magic, then the magic-specific tail (STT3: u32
      // hint; STT4: u32 hint + u32 want_stripes; STTS: u64 token + u8
      // stripe idx — an extra socket attaching to an accepted link)
      uint8_t magic[4];
      set_recv_timeout(fd, 5.0);
      if (!read_full(fd, magic, 4)) {
        ::close(fd);
        continue;
      }
      if (memcmp(magic, kMagicS, 4) == 0) {
        uint8_t rest[9];
        if (!read_full(fd, rest, 9)) {
          ::close(fd);
          continue;
        }
        uint64_t token;
        std::memcpy(&token, rest, 8);
        int idx = rest[8];
        std::shared_ptr<Link> sl;
        {
          StLockGuard lk(node->mu);
          auto now = Clock::now();
          auto& ps = node->pending_stripes;
          for (size_t i = 0; i < ps.size();) {
            if (ps[i].deadline < now || !ps[i].link->alive) {
              ps.erase(ps.begin() + i);
              continue;
            }
            if (ps[i].token == token) sl = ps[i].link;
            i++;
          }
        }
        // reject any index EVER attached (fd stays >= 0 after death; only
        // this acceptor thread writes it for accepted links): a stripe
        // death is permanent by design, and a replayed STTS re-attaching
        // a dead index would reset stripe_io to 2 while the dead pair's
        // exits still owe decrements — driving the refcount to 0 early
        // and closing the NEW fd out from under its fresh I/O threads.
        if (!sl || idx < 1 || idx >= sl->nstripes || !sl->alive ||
            sl->stripe_fd[idx] >= 0) {
          ::close(fd);
          continue;
        }
        uint8_t yy = 'y';
        if (!write_full(fd, &yy, 1)) {
          ::close(fd);
          continue;
        }
        attach_stripe(node, sl, idx, fd);
        continue;
      }
      v4 = memcmp(magic, kMagic4, 4) == 0;
      if (!v4 && memcmp(magic, kMagic, 4) != 0) {
        ::close(fd);
        continue;
      }
      uint8_t rest[8];
      if (!read_full(fd, rest, v4 ? 8 : 4)) {
        ::close(fd);
        continue;
      }
      if (v4) {
        uint32_t w;
        std::memcpy(&w, rest + 4, 4);
        want_stripes =
            (int)(w < 1 ? 1 : (w > (uint32_t)kMaxStripes ? kMaxStripes : w));
      }
    }

    // free child slot? accept. Otherwise redirect down the tree,
    // alternating between children (reference :226-234).
    int slot = -1;
    std::shared_ptr<Link> redirect_to;
    {
      StLockGuard lk(node->mu);
      for (int i = 0; i < node->cfg.max_children; i++) {
        if (!node->child_slot[i]) {
          slot = i;
          break;
        }
      }
      if (slot < 0) {
        // pick an alternating live child for the redirect
        for (int t = 0; t < node->cfg.max_children; t++) {
          int i = (node->lrcounter++) % node->cfg.max_children;
          if (node->child_slot[i]) {
            redirect_to = node->child_slot[i];
            break;
          }
        }
      }
    }
    if (slot >= 0) {
      if (v4) {
        // STT4 accept: 'Y' + [u8 granted][u64 token]; the joiner opens
        // granted-1 extra sockets that attach via the STTS hello above
        uint64_t token;
        {
          StLockGuard lk(node->mu);
          node->token_rng ^= (uint64_t)fd * 0x9e3779b97f4a7c15ull;
          frand64(&node->token_rng);
          token = node->token_rng;
        }
        uint8_t reply[10];
        reply[0] = 'Y';
        reply[1] = (uint8_t)want_stripes;
        std::memcpy(reply + 2, &token, 8);
        if (!write_full(fd, reply, 10)) {
          ::close(fd);
          continue;
        }
        auto link = make_link(node, fd, /*is_uplink=*/0, &peer, want_stripes);
        StLockGuard lk(node->mu);
        node->child_slot[slot] = link;
        if (want_stripes > 1)
          node->pending_stripes.push_back(
              {token, link,
               Clock::now() + std::chrono::duration_cast<Clock::duration>(
                                  std::chrono::seconds(15))});
      } else {
        uint8_t y = 'Y';
        if (!write_full(fd, &y, 1)) {
          ::close(fd);
          continue;
        }
        auto link = make_link(node, fd, /*is_uplink=*/0, &peer);
        StLockGuard lk(node->mu);
        node->child_slot[slot] = link;
      }
    } else if (redirect_to) {
      uint8_t n = 'N';
      sockaddr_in addr = redirect_to->peer_addr;
      write_full(fd, &n, 1);
      write_full(fd, (const uint8_t*)&addr, sizeof addr);
      ::close(fd);
    } else {
      ::close(fd);  // no children to redirect to and no slots (shutting down)
    }
  }
  --node->active_threads;
}

// ---- topology: join walk (reference connect_to, src/sharedtensor.c:
// 244-332) ----------------------------------------------------------------

// Walk the tree from the rendezvous until someone accepts us (O(log N)
// redirects). Returns connected fd + the local endpoint of that socket, or
// -1 with *became_master=true when nobody answers at the rendezvous.
int join_walk(Node* node, sockaddr_in target, bool allow_master,
              bool* became_master, sockaddr_in* local_endpoint,
              int* out_granted, uint64_t* out_token,
              sockaddr_in* out_final) {
  *became_master = false;
  if (out_granted) *out_granted = 1;
  if (out_token) *out_token = 0;
  // STT4 hello iff this node wants stripes (a pre-r11 acceptor rejects it
  // — explicit breakage, the magic-bump discipline; stripe_count=1 keeps
  // the r10 wire byte-for-byte)
  const bool v4 = !node->cfg.wire_compat && node->cfg.stripe_count > 1;
  for (int hops = 0; hops < 64; hops++) {
    int fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return -1;
    set_common_sockopts(fd);
    // bounded per-hop connect (see connect_with_timeout): a dead or
    // silently-dropping target fails this hop after the bound instead of
    // hanging the join forever
    if (!connect_with_timeout(fd, &target, node->cfg.connect_timeout_sec)) {
      ::close(fd);
      if (hops == 0 && allow_master) {
        // nobody home at the rendezvous: we are the master (the reference's
        // master election, src/sharedtensor.c:271-277)
        *became_master = true;
        return -1;
      }
      return -1;
    }
    if (!node->cfg.wire_compat) {
      uint8_t hello[12];
      memcpy(hello, v4 ? kMagic4 : kMagic, 4);
      uint32_t hint = (uint32_t)node->cfg.compat_frame_bytes;
      memcpy(hello + 4, &hint, 4);
      if (v4) {
        uint32_t w = (uint32_t)node->cfg.stripe_count;
        memcpy(hello + 8, &w, 4);
      }
      if (!write_full(fd, hello, v4 ? 12 : 8)) {
        ::close(fd);
        return -1;
      }
    }
    // crash point: connected + hello'd, membership not yet granted
    st_fault_crash_point("mid-join-walk");
    uint8_t reply;
    // the reply read gets the same per-hop bound: an accepting-but-silent
    // peer (half-dead redirect target) must not wedge the walk
    set_recv_timeout(fd, node->cfg.connect_timeout_sec > 0
                             ? node->cfg.connect_timeout_sec
                             : 10.0);
    if (!read_full(fd, &reply, 1)) {
      ::close(fd);
      return -1;
    }
    if (reply == 'Y') {
      if (v4) {
        // STT4 accept tail: [u8 granted][u64 token]
        uint8_t ext[9];
        if (!read_full(fd, ext, 9)) {
          ::close(fd);
          return -1;
        }
        int g = ext[0];
        if (g < 1) g = 1;
        if (g > kMaxStripes) g = kMaxStripes;
        if (out_granted) *out_granted = g;
        if (out_token) std::memcpy(out_token, ext + 1, 8);
      }
      if (out_final) *out_final = target;
      socklen_t len = sizeof *local_endpoint;
      getsockname(fd, (sockaddr*)local_endpoint, &len);
      set_recv_timeout(fd, node->cfg.peer_timeout_sec);
      return fd;
    }
    if (reply != 'N') {
      ::close(fd);
      return -1;
    }
    sockaddr_in next{};
    if (!read_full(fd, (uint8_t*)&next, sizeof next)) {
      ::close(fd);
      return -1;
    }
    ::close(fd);
    target = next;
  }
  return -1;
}

// Open the granted-1 extra stripe sockets toward the accepting hop and
// attach each via the STTS hello. A stripe that fails to connect/ack is
// simply skipped — the link runs on whatever attached (degraded from
// birth beats no link).
void open_stripes(Node* node, const std::shared_ptr<Link>& link,
                  sockaddr_in target, uint64_t token, int granted) {
  for (int i = 1; i < granted && !node->closing && link->alive; i++) {
    int fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) continue;
    set_common_sockopts(fd);
    if (!connect_with_timeout(fd, &target, node->cfg.connect_timeout_sec)) {
      ::close(fd);
      continue;
    }
    uint8_t hello[13];
    memcpy(hello, kMagicS, 4);
    std::memcpy(hello + 4, &token, 8);
    hello[12] = (uint8_t)i;
    uint8_t ack = 0;
    set_recv_timeout(fd, node->cfg.connect_timeout_sec > 0
                             ? node->cfg.connect_timeout_sec
                             : 10.0);
    if (!write_full(fd, hello, 13) || !read_full(fd, &ack, 1) ||
        ack != 'y') {
      ::close(fd);
      continue;
    }
    attach_stripe(node, link, i, fd);
  }
}

// Uplink died: re-graft through the rendezvous (fixes reference quirk Q8 —
// it exits instead). Children keep streaming throughout.
//
// MASTER FAILOVER: when the dead parent was the master itself, nobody
// answers at the rendezvous — every rejoin attempt gets connection-refused.
// An orphan then tries to BIND the rendezvous address and become the new
// master; the OS arbitrates the race between orphaned siblings
// (EADDRINUSE = a sibling won, whom the next join cycle will reach). Only
// a node that can neither join nor bind across two consecutive cycles is
// genuinely isolated (kind-4 event; Python surfaces the error). The
// reference cannot survive a master death at all (quirk Q8).
void rejoin_loop(Node* node) {
  int failed_cycles = 0;
  while (!node->closing) {
    {
      StUniqueLock lk(node->ev_mu);
      node->ev_cv.wait_until(lk.native(), st_cv_deadline(0.2));
    }
    if (node->closing) break;
    bool need;
    {
      StLockGuard lk(node->mu);
      need = !node->is_master && node->uplink_id < 0;
    }
    if (!need) {
      failed_cycles = 0;
      continue;
    }
    bool rejoined = false;
    for (int attempt = 0;
         attempt < node->cfg.max_rejoin_attempts && !node->closing; attempt++) {
      // exponential backoff with +/-50% jitter: orphaned siblings of a dead
      // interior node all start this loop at the same instant; jitter
      // de-synchronizes their walks (and their master-failover bind races)
      std::this_thread::sleep_for(std::chrono::duration<double>(
          node->cfg.rejoin_backoff_sec * (double)(1 << std::min(attempt, 6)) *
          (0.5 + frand64(&node->jrng))));
      bool became_master = false;
      sockaddr_in local{};
      int granted = 1;
      uint64_t token = 0;
      sockaddr_in final_t{};
      int fd = join_walk(node, node->rendezvous, /*allow_master=*/false,
                         &became_master, &local, &granted, &token, &final_t);
      if (fd >= 0) {
        auto l = make_link(node, fd, /*is_uplink=*/1, nullptr, granted);
        if (granted > 1) open_stripes(node, l, final_t, token, granted);
        rejoined = true;
        break;
      }
    }
    if (rejoined || node->closing) {
      failed_cycles = 0;
      continue;
    }
    // Nobody to join: claim the rendezvous (master failover).
    int lfd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (lfd >= 0) {
      set_common_sockopts(lfd);
      sockaddr_in rv = node->rendezvous;
      if (::bind(lfd, (sockaddr*)&rv, sizeof rv) == 0 &&
          ::listen(lfd, node->cfg.listen_backlog) == 0) {
        // Publish under mu with a closing re-check: st_node_close reads
        // rendezvous_listen_fd under the same lock AFTER setting closing,
        // so either we see closing here (and close lfd ourselves) or
        // close() sees the published fd — a bound rendezvous socket can
        // never leak past shutdown.
        bool published = false;
        {
          StLockGuard lk(node->mu);
          if (!node->closing) {
            node->is_master = true;
            node->rendezvous_listen_fd = lfd;
            published = true;
          }
        }
        if (!published) {
          ::close(lfd);
          break;
        }
        node->active_threads += 1;
        std::thread(listener_loop, node, lfd).detach();
        node->emit(3, 0, 0);  // became master: Python flips its role
        failed_cycles = 0;
        continue;
      }
      ::close(lfd);  // EADDRINUSE: a sibling won the race (or foreign IP)
    }
    if (++failed_cycles >= 2) {
      node->emit(4, 0, 1);  // isolated: cannot join OR claim the rendezvous
      failed_cycles = 0;    // keep trying, but don't spam the event
    }
  }
  --node->active_threads;
}

}  // namespace

// ---- C ABI ---------------------------------------------------------------

extern "C" {

typedef struct StNodeHandle StNodeHandle;

struct StConfigC {
  int32_t wire_compat;
  int32_t compat_frame_bytes;
  int32_t listen_backlog;
  int64_t bandwidth_cap_bps;
  double peer_timeout_sec;
  double keepalive_sec;
  int32_t max_children;
  int32_t queue_depth;
  int32_t max_rejoin_attempts;
  double rejoin_backoff_sec;
  double connect_timeout_sec;  // per-hop connect/reply bound (0 = blocking)
  double join_timeout_sec;     // total create-time join budget (0 = 30 s)
  int32_t stripe_count;        // r11: sockets per logical link (1..8)
};

struct StEventC {
  int32_t kind;
  int32_t link_id;
  int32_t is_uplink;
};

struct StStatsC {
  uint64_t bytes_out, bytes_in, frames_out, frames_in;
  int32_t send_queue, recv_queue;
};

// Create a node and join the tree at host:port (or become master when nobody
// answers). Returns NULL on error. is_master receives 1/0.
void* st_node_create(const char* host, int port, const StConfigC* cfg_c,
                     int32_t* is_master) {
  if (cfg_c->wire_compat && cfg_c->compat_frame_bytes < 5) {
    return nullptr;  // compat frames are [f32 scale][>=1 bitmask byte]
  }
  auto* node = new Node();
  node->obs_id =
      stobs::g_node_id_base |
      ((stobs::g_next_node_local.fetch_add(1, std::memory_order_relaxed) +
        1u) &
       0xFFFu);
  Config& cfg = node->cfg;
  cfg.wire_compat = cfg_c->wire_compat;
  cfg.compat_frame_bytes = cfg_c->compat_frame_bytes;
  cfg.listen_backlog = cfg_c->listen_backlog;
  cfg.bandwidth_cap_bps = cfg_c->bandwidth_cap_bps;
  cfg.peer_timeout_sec = cfg_c->peer_timeout_sec;
  cfg.keepalive_sec = cfg_c->keepalive_sec;
  cfg.max_children = std::min<int32_t>(cfg_c->max_children, 16);
  cfg.queue_depth = cfg_c->queue_depth;
  cfg.max_rejoin_attempts = cfg_c->max_rejoin_attempts;
  cfg.rejoin_backoff_sec = cfg_c->rejoin_backoff_sec;
  cfg.connect_timeout_sec = cfg_c->connect_timeout_sec;
  cfg.join_timeout_sec = cfg_c->join_timeout_sec;
  // striping is native-framing only (the reference compat protocol has
  // one stream per link by definition)
  cfg.stripe_count = cfg_c->stripe_count < 1
                         ? 1
                         : (cfg_c->stripe_count > kMaxStripes
                                ? kMaxStripes
                                : cfg_c->stripe_count);
  if (cfg.wire_compat) cfg.stripe_count = 1;
  cfg.fault = parse_fault_plan();  // env hook table, per-node at create
  node->jrng = (uint64_t)::getpid() * 0x9e3779b97f4a7c15ull +
               (uint64_t)Clock::now().time_since_epoch().count();
  {
    // no thread exists yet; the lock is for the analysis' benefit (and
    // costs one uncontended acquisition at create)
    StLockGuard lk(node->mu);
    node->token_rng = node->jrng ^ 0xA5A5A5A5DEADBEEFull;
  }

  hostent* server = gethostbyname(host);
  if (!server) {
    node->last_error = "no such host";
    delete node;
    return nullptr;
  }
  sockaddr_in target{};
  target.sin_family = AF_INET;
  memcpy(&target.sin_addr.s_addr, server->h_addr, server->h_length);
  target.sin_port = htons((uint16_t)port);
  node->rendezvous = target;

  // Join-or-become-master, with retry. Two races both end in a failed
  // first pass and both resolve by retrying as a joiner (the reference
  // inherits the same race and just dies, src/sharedtensor.c:271-277,314):
  //  - A and B start together; both find the rendezvous empty, both elect
  //    themselves master; one loses the bind (EADDRINUSE) — the loser must
  //    re-walk, and will now connect to the winner.
  //  - A joins while B (the would-be master) is between its failed connect
  //    and its listen(): A's walk fails outright; a short backoff later the
  //    master is listening.
  bool became_master = false;
  int up_fd = -1;
  int listen_fd = -1;
  int up_granted = 1;
  uint64_t up_token = 0;
  sockaddr_in up_final{};
  // Bounded join-or-become-master: a TOTAL deadline (join_timeout_sec)
  // replaces the old fixed 50-attempt loop, and retries back off
  // exponentially with +/-50% jitter — a herd of simultaneous joiners (or
  // the two election races above) must not re-collide in lockstep. Before
  // r06, an unreachable-but-not-refusing rendezvous hung the first
  // connect() forever; now every hop is bounded (connect_with_timeout)
  // and the whole loop gives up at the deadline, surfacing a
  // ConnectionError to Python instead of a wedged constructor.
  double budget = cfg.join_timeout_sec > 0 ? cfg.join_timeout_sec : 30.0;
  auto deadline = Clock::now() + std::chrono::duration<double>(budget);
  uint64_t jrng = node->jrng;
  for (int attempt = 0; attempt < 1000 && !listen_fd_ok(listen_fd);
       attempt++) {
    if (attempt > 0) {
      if (Clock::now() >= deadline) break;
      double base = 0.01 * (double)(1 << std::min(attempt - 1, 7));
      if (base > 2.0) base = 2.0;
      double sleep_s = base * (0.5 + frand64(&jrng));
      double rem =
          std::chrono::duration<double>(deadline - Clock::now()).count();
      if (sleep_s > rem) sleep_s = rem > 0 ? rem : 0;
      std::this_thread::sleep_for(std::chrono::duration<double>(sleep_s));
    }
    became_master = false;
    sockaddr_in listen_addr{};
    up_fd = join_walk(node, target, /*allow_master=*/true, &became_master,
                      &listen_addr, &up_granted, &up_token, &up_final);
    if (up_fd < 0 && !became_master) continue;  // tree settling; retry
    if (became_master) listen_addr = target;  // master owns the rendezvous addr

    // Bind the listen socket to the same endpoint our parent observed (the
    // reference's addressing trick) so redirects that hand out our accept()-
    // observed address reach our listener.
    listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
    set_common_sockopts(listen_fd);
    if (::bind(listen_fd, (sockaddr*)&listen_addr, sizeof listen_addr) < 0 ||
        ::listen(listen_fd, cfg.listen_backlog) < 0) {
      // lost the master election (or our observed endpoint got reused):
      // close everything and re-walk as a joiner
      ::close(listen_fd);
      listen_fd = -1;
      if (up_fd >= 0) {
        ::close(up_fd);
        up_fd = -1;
      }
      continue;
    }
  }
  if (!listen_fd_ok(listen_fd)) {
    if (up_fd >= 0) ::close(up_fd);
    delete node;
    return nullptr;
  }
  {
    StLockGuard lk(node->mu);  // pre-thread, for the analysis (see above)
    node->is_master = became_master;
  }
  node->listen_fd = listen_fd;

  node->active_threads += 2;
  std::thread(listener_loop, node, listen_fd).detach();
  std::thread(rejoin_loop, node).detach();
  if (up_fd >= 0) {
    auto l = make_link(node, up_fd, /*is_uplink=*/1, nullptr, up_granted);
    if (up_granted > 1) open_stripes(node, l, up_final, up_token, up_granted);
  }
  if (is_master) *is_master = became_master ? 1 : 0;
  if (became_master) node->emit(3, 0, 0);
  return node;
}

// The node's process-unique obs id (tags its events on the shared rings).
uint32_t st_node_obs_id(void* h) {
  auto* node = (Node*)h;
  return node ? node->obs_id : 0;
}

int32_t st_node_listen_port(void* h) {
  auto* node = (Node*)h;
  sockaddr_in addr{};
  socklen_t len = sizeof addr;
  if (getsockname(node->listen_fd, (sockaddr*)&addr, &len) < 0) return -1;
  return (int32_t)ntohs(addr.sin_port);
}

// Enqueue a frame for a link. Returns 1 on success, 0 if the queue stayed
// full for timeout_sec (backpressure — caller should retry), -1 dead link.
int32_t st_node_send(void* h, int32_t link_id, const uint8_t* data,
                     int32_t len, double timeout_sec) {
  auto* node = (Node*)h;
  // Compat payload contract: K >= 1 whole reference frames, exactly
  // K * compat_frame_bytes. The sender loop's frames_out accounting
  // divides by compat_frame_bytes (integer), and the receiver re-frames
  // the stream in fixed-size chunks — a non-multiple payload would both
  // undercount silently and shear every later frame boundary on the
  // receiver, so reject it at the enqueue boundary.
  if (node->cfg.wire_compat && node->cfg.compat_frame_bytes > 0 &&
      (len <= 0 || len % node->cfg.compat_frame_bytes != 0))
    return -1;
  std::shared_ptr<Link> link;
  {
    StLockGuard lk(node->mu);
    auto it = node->links.find(link_id);
    if (it == node->links.end()) return -1;
    link = it->second;
  }
  if (!link->alive) return -1;
  // ONE copy at the ABI boundary, into a recycled buffer (the bytes must
  // outlive the caller's, e.g. a Python bytes object, until the socket
  // write) — the old path allocated a fresh vector per message
  bool hit = false;
  OutMsg msg;
  msg.owned = link->tx_pool.get(&hit);
  node->tx_acquires++;
  if (!hit) node->tx_pool_misses++;
  msg.owned.assign(data, data + len);
  Link* lp = link.get();
  if (link->sendq.push_hook(std::move(msg), timeout_sec, [lp](OutMsg& m) {
        // stripe-seq stamp, under the queue mutex at insertion (r11): a
        // stamped seq is always eventually written, so reassembly never
        // waits on a hole
        m.sseq = lp->sseq_next.fetch_add(1, std::memory_order_relaxed);
      }))
    return 1;
  return 0;
}

// Zero-copy enqueue (the native engine's tx-ring path): the transport
// borrows [data, data+len) — NO copy is made — and calls release(ctx)
// exactly once when the bytes have left the socket (or the link died with
// the message queued; teardown releases via OutMsg's destructor). Returns
// 1 = enqueued (transport now owns one reference), 0 = backpressure and
// -1 = dead link (in both of which the transport took NO ownership and
// will never call release — the caller retains its reference).
int32_t st_node_send_zc(void* h, int32_t link_id, const uint8_t* data,
                        int32_t len, double timeout_sec,
                        void (*release)(void*), void* ctx) {
  auto* node = (Node*)h;
  if (node->cfg.wire_compat) return -1;  // compat framing has no zc path
  std::shared_ptr<Link> link;
  {
    StLockGuard lk(node->mu);
    auto it = node->links.find(link_id);
    if (it == node->links.end()) return -1;
    link = it->second;
  }
  if (!link->alive) return -1;
  OutMsg msg;
  msg.zdata = data;
  msg.zlen = (uint32_t)len;
  msg.release = release;
  msg.ctx = ctx;
  Link* lp = link.get();
  if (link->sendq.push_hook(std::move(msg), timeout_sec, [lp](OutMsg& m) {
        m.sseq = lp->sseq_next.fetch_add(1, std::memory_order_relaxed);
      })) {
    node->zc_msgs++;
    return 1;
  }
  // not enqueued: disarm before msg destructs — ownership stays with the
  // caller on every non-1 return
  msg.release = nullptr;
  return link->alive ? 0 : -1;
}

// Dequeue a received frame. Returns payload length (copied into buf up to
// cap), 0 if none within timeout, -1 if the link is dead AND drained.
int32_t st_node_recv(void* h, int32_t link_id, uint8_t* buf, int32_t cap,
                     double timeout_sec) {
  auto* node = (Node*)h;
  std::shared_ptr<Link> link;
  {
    StLockGuard lk(node->mu);
    auto it = node->links.find(link_id);
    if (it == node->links.end()) return -1;
    link = it->second;
  }
  std::vector<uint8_t> frame;
  if (!link->recvq.pop(&frame, timeout_sec)) {
    return link->alive ? 0 : -1;
  }
  int32_t n = (int32_t)std::min<size_t>(frame.size(), (size_t)cap);
  memcpy(buf, frame.data(), (size_t)n);
  link->rx_pool.put(std::move(frame));  // recycle, capacity warm
  return n;
}

// Zero-copy receive (r14): like st_node_recv, but instead of copying into
// the caller's buffer the popped rx buffer is LOANED — *out points at its
// bytes and the return value is its length. The pointer stays valid until
// the next st_node_recv_zc / st_node_recv_done on the same link (loans
// live on the NODE, so a link torn down mid-parse cannot free them).
// Exactly one loan per link; the native engine's receiver is the intended
// caller (one message in hand at a time per link).
int32_t st_node_recv_zc(void* h, int32_t link_id, const uint8_t** out,
                        double timeout_sec) {
  auto* node = (Node*)h;
  *out = nullptr;
  std::vector<uint8_t> prev;
  {
    StLockGuard lk(node->loan_mu);
    auto it = node->loans.find(link_id);
    if (it != node->loans.end()) {
      prev = std::move(it->second);
      node->loans.erase(it);
    }
  }
  std::shared_ptr<Link> link;
  {
    StLockGuard lk(node->mu);
    auto it = node->links.find(link_id);
    if (it != node->links.end()) link = it->second;
  }
  if (prev.capacity() && link) link->rx_pool.put(std::move(prev));
  if (!link) return -1;
  std::vector<uint8_t> frame;
  if (!link->recvq.pop(&frame, timeout_sec)) {
    return link->alive ? 0 : -1;
  }
  int32_t n = (int32_t)frame.size();
  {
    StLockGuard lk(node->loan_mu);
    auto& slot = node->loans[link_id];
    slot = std::move(frame);
    *out = slot.data();
  }
  return n;
}

// Release a link's outstanding recv_zc loan (recycling its buffer when
// the link still exists). Call when done draining a link; harmless when
// no loan is out.
void st_node_recv_done(void* h, int32_t link_id) {
  auto* node = (Node*)h;
  if (!node) return;
  std::vector<uint8_t> prev;
  {
    StLockGuard lk(node->loan_mu);
    auto it = node->loans.find(link_id);
    if (it != node->loans.end()) {
      prev = std::move(it->second);
      node->loans.erase(it);
    }
  }
  if (!prev.capacity()) return;
  std::shared_ptr<Link> link;
  {
    StLockGuard lk(node->mu);
    auto it = node->links.find(link_id);
    if (it != node->links.end()) link = it->second;
  }
  if (link) link->rx_pool.put(std::move(prev));
}

// r17 engine-tier shard plane: ownership-transfer receive, the transport
// half of the zero-copy verbatim relay. Like st_node_recv_zc, but the
// popped rx buffer's OWNERSHIP moves to the caller: *out points at its
// bytes, *tok receives an opaque owner token the caller releases with
// st_node_take_free(h, link_id, tok) exactly once (recycling the buffer
// into the link's rx pool when the link still exists, so the steady
// state stays allocation-free). The shard plane's relay path is the
// intended caller: a FWD frame whose owner is downstream is re-stamped
// IN PLACE (per-link seq only — the bytes are never decoded) and
// enqueued via st_node_send_zc straight from this same buffer, held
// through go-back-N retention — which makes relays ordinary zero-copy
// sends, eligible for sendmmsg batching and the r14 shm lane like any
// slot-backed message. No loan bookkeeping: the token outlives any
// number of recv calls on the link.
int32_t st_node_recv_take(void* h, int32_t link_id, const uint8_t** out,
                          void** tok) {
  auto* node = (Node*)h;
  *out = nullptr;
  *tok = nullptr;
  std::shared_ptr<Link> link;
  {
    StLockGuard lk(node->mu);
    auto it = node->links.find(link_id);
    if (it != node->links.end()) link = it->second;
  }
  if (!link) return -1;
  std::vector<uint8_t> frame;
  if (!link->recvq.pop(&frame, 0.0)) {
    return link->alive ? 0 : -1;
  }
  auto* owner = new std::vector<uint8_t>(std::move(frame));
  *out = owner->data();
  *tok = owner;
  return (int32_t)owner->size();
}

// Release a buffer taken with st_node_recv_take (exactly once). The link
// id routes the recycle back into the owning link's rx pool; a link torn
// down in the meantime just frees the buffer.
void st_node_take_free(void* h, int32_t link_id, void* tok) {
  auto* owner = (std::vector<uint8_t>*)tok;
  if (!owner) return;
  auto* node = (Node*)h;
  std::shared_ptr<Link> link;
  if (node) {
    StLockGuard lk(node->mu);
    auto it = node->links.find(link_id);
    if (it != node->links.end()) link = it->second;
  }
  if (link) link->rx_pool.put(std::move(*owner));
  delete owner;
}

// Free slots in the link's send queue (-1 unknown link). The shard
// plane's outbox pump keeps control-traffic headroom with this — the
// python tier's _queue_room discipline: a data pump that races the
// cumulative ACKs and shard control messages for the last sendq slot
// starves the very ACKs that drain its own ledger.
int32_t st_node_sendq_room(void* h, int32_t link_id) {
  auto* node = (Node*)h;
  std::shared_ptr<Link> link;
  {
    StLockGuard lk(node->mu);
    auto it = node->links.find(link_id);
    if (it == node->links.end()) return -1;
    link = it->second;
  }
  int32_t depth = node->cfg.queue_depth;
  int32_t used = (int32_t)link->sendq.size();
  return used >= depth ? 0 : depth - used;
}

// r07 pool/zero-copy observability:
// out[0..1] tx buffer acquires / misses (fresh allocations),
// out[2..3] rx buffer acquires / misses, out[4] zero-copy sends enqueued.
// Steady state must show acquires growing while misses stay flat — the
// "zero per-message heap allocations" assertion peer.metrics() surfaces.
void st_node_pool_stats(void* h, uint64_t* out5) {
  auto* node = (Node*)h;
  if (!node) {
    for (int i = 0; i < 5; i++) out5[i] = 0;
    return;
  }
  out5[0] = node->tx_acquires.load();
  out5[1] = node->tx_pool_misses.load();
  out5[2] = node->rx_acquires.load();
  out5[3] = node->rx_pool_misses.load();
  out5[4] = node->zc_msgs.load();
}

// r11 per-link stripe telemetry: out4[0] = negotiated stripe count,
// out4[1] = live stripes, out4[2] = stripe deaths on this link,
// out4[3] = messages re-routed off a dying stripe. Returns -1 for an
// unknown link.
int32_t st_node_stripe_stats(void* h, int32_t link_id, uint64_t* out4) {
  auto* node = (Node*)h;
  for (int i = 0; i < 4; i++) out4[i] = 0;
  if (!node) return -1;
  std::shared_ptr<Link> link;
  {
    StLockGuard lk(node->mu);
    auto it = node->links.find(link_id);
    if (it == node->links.end()) return -1;
    link = it->second;
  }
  out4[0] = (uint64_t)link->nstripes;
  out4[1] = (uint64_t)(link->stripes_live.load() < 0
                           ? 0
                           : link->stripes_live.load());
  out4[2] = link->stripe_deaths.load();
  out4[3] = link->reroutes.load();
  return 0;
}

// ---- r14 same-host shm lane ABI ------------------------------------------

// CREATE the link's shm segment (the parent's half of the negotiated
// attach): a /dev/shm file holding one header page + two rings of
// ring_bytes each. Writes the segment basename into name_out and the
// validation token into token_out; the peer passes both to
// st_node_shm_join. The data plane switches lanes only once the joiner
// has mapped and validated (Hdr::joined) — until then, and forever on
// failure, the link keeps streaming on TCP. Returns 0, or -1 (bad
// link/mode/state) / -2 (segment creation failed).
int32_t st_node_shm_serve(void* h, int32_t link_id, int64_t ring_bytes,
                          char* name_out, int32_t name_cap,
                          uint64_t* token_out) {
  auto* node = (Node*)h;
  if (!node || node->cfg.wire_compat) return -1;
  // a PER-STRIPE fault plan (only_stripe >= 0) is a TCP-striping
  // diagnostic — the lane's single-writer data plane would mask it, so
  // the chaos arm pins the link to TCP (link-wide fault classes apply on
  // the lane writer and stay fully covered)
  if (node->cfg.fault.enabled && node->cfg.fault.only_stripe >= 0)
    return -1;
  std::shared_ptr<Link> link;
  {
    StLockGuard lk(node->mu);
    auto it = node->links.find(link_id);
    if (it != node->links.end()) link = it->second;
  }
  if (!link || !link->alive ||
      link->shm.load(std::memory_order_acquire) != nullptr)
    return -1;
  if (ring_bytes < (1 << 16)) ring_bytes = 1 << 16;
  if (ring_bytes > (1 << 30)) ring_bytes = 1 << 30;
  ring_bytes = (ring_bytes + 4095) & ~(int64_t)4095;

  uint64_t tok;
  {
    StLockGuard lk(node->mu);
    node->token_rng ^=
        ((uint64_t)link_id << 32) * 0x9e3779b97f4a7c15ull + (uint64_t)getpid();
    frand64(&node->token_rng);
    tok = node->token_rng;
  }
  char name[96];
  snprintf(name, sizeof name, "stshm-%d-%d-%016llx", (int)getpid(),
           (int)link_id, (unsigned long long)tok);
  if ((int32_t)strlen(name) + 1 > name_cap) return -1;
  std::string path = std::string("/dev/shm/") + name;
  int fd = ::open(path.c_str(), O_RDWR | O_CREAT | O_EXCL | O_CLOEXEC, 0600);
  if (fd < 0) return -2;
  size_t map_len = stshm::kDataOff + 2 * (size_t)ring_bytes;
  if (::ftruncate(fd, (off_t)map_len) != 0) {
    ::close(fd);
    ::unlink(path.c_str());
    return -2;
  }
  void* base =
      ::mmap(nullptr, map_len, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  ::close(fd);
  if (base == MAP_FAILED) {
    ::unlink(path.c_str());
    return -2;
  }
  auto* hd = new (base) stshm::Hdr();  // placement-init the atomics
  hd->magic = stshm::kMagic;
  hd->version = stshm::kVersion;
  hd->ring_bytes = (uint32_t)ring_bytes;
  hd->token = tok;

  auto* lane = new stshm::Lane();
  lane->hdr = hd;
  lane->data[0] = (uint8_t*)base + stshm::kDataOff;
  lane->data[1] = (uint8_t*)base + stshm::kDataOff + (size_t)ring_bytes;
  lane->map_len = map_len;
  lane->ring_bytes = (uint32_t)ring_bytes;
  lane->creator = 1;
  lane->name = name;
  // striped links reassemble by stripe seq, so ring delivery may start
  // immediately; unstriped delivery waits for the in-stream SWITCH marker
  lane->rx_go.store(link->nstripes > 1, std::memory_order_release);
  link->shm.store(lane, std::memory_order_release);
  node->active_threads += 1;
  std::thread(shm_rx_loop, node, link).detach();
  snprintf(name_out, (size_t)name_cap, "%s", name);
  if (token_out) *token_out = tok;
  return 0;
}

// JOIN the peer's shm segment by name+token (the child's half). On
// success the segment name is immediately unlinked (it cannot outlive the
// two mappings), Hdr::joined flips the creator's tx lane live, and this
// side's tx activates at its sender's next pop. On ANY failure the link
// keeps TCP and a shm_fallback event records why (arg: 1 open, 2 map,
// 3 header/token mismatch).
int32_t st_node_shm_join(void* h, int32_t link_id, const char* name,
                         uint64_t token) {
  auto* node = (Node*)h;
  if (!node || node->cfg.wire_compat || !name) return -1;
  // per-stripe chaos pins TCP on the joining side too (see shm_serve)
  if (node->cfg.fault.enabled && node->cfg.fault.only_stripe >= 0)
    return -1;
  std::shared_ptr<Link> link;
  {
    StLockGuard lk(node->mu);
    auto it = node->links.find(link_id);
    if (it != node->links.end()) link = it->second;
  }
  if (!link || !link->alive ||
      link->shm.load(std::memory_order_acquire) != nullptr)
    return -1;
  // the name is peer-supplied: confine it to our own flat namespace
  if (strncmp(name, "stshm-", 6) != 0 || strchr(name, '/') != nullptr ||
      strstr(name, "..") != nullptr || strlen(name) > 80) {
    st_obs_emit(node->obs_id, stobs::kEvShmFallback, link_id, 3);
    return -3;
  }
  std::string path = std::string("/dev/shm/") + name;
  int fd = ::open(path.c_str(), O_RDWR | O_CLOEXEC);
  if (fd < 0) {
    st_obs_emit(node->obs_id, stobs::kEvShmFallback, link_id, 1);
    return -1;
  }
  struct stat st;
  if (::fstat(fd, &st) != 0 ||
      (size_t)st.st_size < stshm::kDataOff + 2 * (1 << 16)) {
    ::close(fd);
    st_obs_emit(node->obs_id, stobs::kEvShmFallback, link_id, 2);
    return -2;
  }
  size_t map_len = (size_t)st.st_size;
  void* base =
      ::mmap(nullptr, map_len, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  ::close(fd);
  if (base == MAP_FAILED) {
    st_obs_emit(node->obs_id, stobs::kEvShmFallback, link_id, 2);
    return -2;
  }
  auto* hd = (stshm::Hdr*)base;
  if (hd->magic != stshm::kMagic || hd->version != stshm::kVersion ||
      hd->token != token ||
      stshm::kDataOff + 2 * (size_t)hd->ring_bytes != map_len) {
    ::munmap(base, map_len);
    st_obs_emit(node->obs_id, stobs::kEvShmFallback, link_id, 3);
    return -3;
  }
  ::unlink(path.c_str());  // leak-proof: the name dies with this map

  auto* lane = new stshm::Lane();
  lane->hdr = hd;
  lane->data[0] = (uint8_t*)base + stshm::kDataOff;
  lane->data[1] = (uint8_t*)base + stshm::kDataOff + hd->ring_bytes;
  lane->map_len = map_len;
  lane->ring_bytes = hd->ring_bytes;
  lane->creator = 0;
  lane->rx_go.store(link->nstripes > 1, std::memory_order_release);
  link->shm.store(lane, std::memory_order_release);
  node->active_threads += 1;
  std::thread(shm_rx_loop, node, link).detach();
  // publish LAST: the creator's senders switch lanes on observing this
  hd->joined.store(1, std::memory_order_release);
  stshm::futex_wake_all(&hd->ring[0].head_seq);
  return 0;
}

// r14 shm lane telemetry: out8[0] = lane state (0 = TCP only, 1 = segment
// mapped, 2 = tx live), [1..2] = messages out/in over the lane, [3..4] =
// lane bytes out/in (record headers included), [5] = ring bytes per
// direction, [6..7] = tx/rx futex sleeps (the spin-before-sleep misses).
// Returns -1 for an unknown link.
int32_t st_node_shm_stats(void* h, int32_t link_id, uint64_t* out8) {
  auto* node = (Node*)h;
  for (int i = 0; i < 8; i++) out8[i] = 0;
  if (!node) return -1;
  std::shared_ptr<Link> link;
  {
    StLockGuard lk(node->mu);
    auto it = node->links.find(link_id);
    if (it == node->links.end()) return -1;
    link = it->second;
  }
  stshm::Lane* sl = link->shm.load(std::memory_order_acquire);
  if (!sl) return 0;
  out8[0] = sl->tx_ready() ? 2 : 1;
  out8[1] = sl->msgs_out.load();
  out8[2] = sl->msgs_in.load();
  out8[3] = sl->bytes_out.load();
  out8[4] = sl->bytes_in.load();
  out8[5] = (uint64_t)sl->ring_bytes;
  out8[6] = sl->tx_waits.load();
  out8[7] = sl->rx_waits.load();
  return 0;
}

int32_t st_node_poll_events(void* h, StEventC* out, int32_t cap,
                            double timeout_sec) {
  auto* node = (Node*)h;
  StUniqueLock lk(node->ev_mu);
  if (node->events.empty() && timeout_sec > 0) {
    node->ev_cv.wait_until(lk.native(), st_cv_deadline(timeout_sec));
  }
  int32_t n = 0;
  while (n < cap && !node->events.empty()) {
    Event e = node->events.front();
    node->events.pop_front();
    out[n].kind = e.kind;
    out[n].link_id = e.link_id;
    out[n].is_uplink = e.is_uplink;
    n++;
  }
  return n;
}

int32_t st_node_links(void* h, int32_t* out, int32_t cap) {
  auto* node = (Node*)h;
  StLockGuard lk(node->mu);
  int32_t n = 0;
  for (auto& kv : node->links) {
    if (n >= cap) break;
    out[n++] = kv.first;
  }
  return n;
}

int32_t st_node_uplink(void* h) {
  auto* node = (Node*)h;
  StLockGuard lk(node->mu);
  return node->uplink_id;
}

int32_t st_node_stats(void* h, int32_t link_id, StStatsC* out) {
  auto* node = (Node*)h;
  std::shared_ptr<Link> link;
  {
    StLockGuard lk(node->mu);
    auto it = node->links.find(link_id);
    if (it == node->links.end()) return -1;
    link = it->second;
  }
  out->bytes_out = link->bytes_out;
  out->bytes_in = link->bytes_in;
  out->frames_out = link->frames_out;
  out->frames_in = link->frames_in;
  out->send_queue = (int32_t)link->sendq.size();
  out->recv_queue = (int32_t)link->recvq.size();
  return 0;
}

// Data-arrival sequence number: bumps whenever any link delivers a frame
// into its recv queue (or a link dies). Pair with st_node_wait_data for
// blocking multi-link consumption without per-queue polling.
uint64_t st_node_data_seq(void* h) {
  auto* node = (Node*)h;
  StLockGuard lk(node->data_mu);
  return node->data_seq;
}

// Block until the data sequence advances past last_seq (returns the new
// value), or timeout (returns the current value). A caller that drains the
// queues, then waits on the seq it read BEFORE draining, can never miss a
// wakeup.
uint64_t st_node_wait_data(void* h, uint64_t last_seq, double timeout_sec) {
  auto* node = (Node*)h;
  StUniqueLock lk(node->data_mu);
  if (timeout_sec > 0) {
    // explicit deadline loop (not wait_for-with-predicate): the predicate
    // lambda would read the guarded data_seq from a context the
    // thread-safety analysis treats as lock-free
    const auto deadline = st_cv_deadline(timeout_sec);
    while (node->data_seq <= last_seq &&
           node->data_cv.wait_until(lk.native(), deadline) !=
               std::cv_status::timeout) {
    }
  }
  return node->data_seq;
}

// Drop one link deliberately (tests / fault injection).
int32_t st_node_drop_link(void* h, int32_t link_id) {
  auto* node = (Node*)h;
  std::shared_ptr<Link> link;
  {
    StLockGuard lk(node->mu);
    auto it = node->links.find(link_id);
    if (it == node->links.end()) return -1;
    link = it->second;
  }
  kill_link(node, link);
  return 0;
}

void st_node_close(void* h) {
  auto* node = (Node*)h;
  node->closing = true;
  ::shutdown(node->listen_fd, SHUT_RDWR);
  ::close(node->listen_fd);
  int rv_fd;
  {
    StLockGuard lk(node->mu);
    rv_fd = node->rendezvous_listen_fd;
  }
  if (rv_fd >= 0) {
    ::shutdown(rv_fd, SHUT_RDWR);
    ::close(rv_fd);
  }
  std::vector<std::shared_ptr<Link>> links;
  {
    StLockGuard lk(node->mu);
    for (auto& kv : node->links) links.push_back(kv.second);
  }
  for (auto& l : links) kill_link(node, l);
  node->ev_cv.notify_all();
  node->notify_data();  // unblock any engine waiting in st_node_wait_data
  // All threads are detached; wait (bounded) for them to drain.
  for (int i = 0; i < 1000 && node->active_threads > 0; i++) {
    std::this_thread::sleep_for(std::chrono::milliseconds(10));
  }
  if (node->active_threads == 0) {
    delete node;
  }
  // else: leak the node rather than free memory under a live thread —
  // cannot happen unless a peer wedges a write for >10s during shutdown.
}

}  // extern "C"
