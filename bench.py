"""Headline benchmark: approximate-delta sync bandwidth of the fused codec
path on one chip, in equivalent applied-fp32-delta GB/s per link.

Methodology (matches BASELINE.md's yardstick): the reference's 2-node
loopback E2E sync at n = 1 Mi elements moves 1.01 GB/s of equivalent fp32
deltas per link, and is codec-CPU-bound, not network-bound (SURVEY.md §6 —
the wire carries only 0.03 GB/s; one core saturates on the quantize/apply
loops, which is exactly the work reference README.md:47 wanted moved to an
accelerator kernel). This bench therefore times that bottleneck work on the
TPU: per frame, one full sender half (pow2-RMS scale + sign-quantize +
bit-pack + error feedback, Pallas) plus one receiver half (unpack + apply,
Pallas) on an n = 1 Mi buffer — the identical per-link per-frame math at
identical approximation error (the codec is bit-for-bit the reference
arithmetic; tests/test_codec*.py pin that). Frames are chained device-side
via lax.fori_loop into multi-second runs so tunnel dispatch latency is a
small bias that only understates the result; gaussian residuals keep a
nonzero scale throughout, so every frame does the full (non-idle) codec work.

Robustness contract (round-1 postmortem, VERDICT.md): this process NEVER
imports jax itself. Every measurement runs in a watchdogged subprocess with
a hard timeout, under a total wall-clock budget (ST_BENCH_BUDGET_S, default
420 s); a wedged TPU tunnel (observed: jax.devices() hanging forever) can
kill an arm but not the bench. Arm ladder: real chip + Pallas (the headline;
retried with backoff if the chip is claimed/wedged) -> real chip + XLA codec
(only if the backend came up but Mosaic failed) -> CPU + native engine E2E
(the host production data plane, 2-process loopback through the FULL stack —
the measurement that matches the baseline's own E2E methodology, ~4x the
reference; degraded-labeled) -> CPU + host codec component loop (numpy/
AVX-512-C, jax-free, ~2.9x) -> CPU + XLA (last resort). Exactly ONE JSON
line is always printed, recording which arms ran and how each ended
(detail.attempts / detail.chip_state).
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

N = 1 << 20  # 1 Mi elements — BASELINE.md's headline E2E config
BASELINE_GBPS = 1.01
BUDGET_S = float(os.environ.get("ST_BENCH_BUDGET_S", "420"))
CPU_RESERVE_S = 130.0  # budget held back for the CPU fallback arms
_T0 = time.monotonic()
_PRINTED = False
_ACTIVE_WORKER: "subprocess.Popen | None" = None


def _remaining() -> float:
    return BUDGET_S - (time.monotonic() - _T0)


def _kill_worker_tree(proc: "subprocess.Popen") -> None:
    """Kill a worker AND its whole process group (engine-arm grandchildren)."""
    try:
        os.killpg(proc.pid, signal.SIGKILL)
    except (OSError, ProcessLookupError):
        try:
            proc.kill()
        except OSError:
            pass


def _emit(result: dict) -> None:
    global _PRINTED
    if not _PRINTED:
        _PRINTED = True
        print(json.dumps(result), flush=True)


def _error_result(attempts, reason: str) -> dict:
    return {
        "metric": "sync_bandwidth_equiv_fp32_per_link",
        "value": 0.0,
        "unit": "GB/s",
        "vs_baseline": 0.0,
        "tier": "none",
        "detail": {"error": reason, "attempts": attempts},
    }


def _print_result(t_frame: float, backend: str, codec_name: str) -> None:
    """One schema for every worker arm (host and jax) — the supervisor and
    the round artifacts parse this."""
    fps = 1.0 / t_frame
    equiv_gbps = fps * N * 4 / 1e9
    print(
        json.dumps(
            {
                "metric": "sync_bandwidth_equiv_fp32_per_link",
                "value": round(equiv_gbps, 3),
                "unit": "GB/s",
                "vs_baseline": round(equiv_gbps / BASELINE_GBPS, 2),
                "detail": {
                    "n_elements": N,
                    "frames_per_s": round(fps, 1),
                    "backend": backend,
                    "codec": codec_name,
                    "wire_gbps": round(fps * (N / 8 + 4) / 1e9, 4),
                },
            }
        ),
        flush=True,
    )


# ---------------------------------------------------------------- worker ----


def _worker(codec_name: str) -> None:
    """Runs in a subprocess: init backend, announce it, measure, print JSON."""
    if codec_name == "engine":
        _worker_engine()
        return
    if codec_name == "host":
        # The host tier must NOT initialize a jax backend: the XLA CPU
        # client's thread pool contends with the C codec loops on a small
        # host (measured on this 1-vCPU box: 6.2 ms/frame with a live
        # backend vs 2.26 ms without — 2.7x).
        _worker_host()
        return

    import jax

    # The ambient TPU-plugin site hook overrides the JAX_PLATFORMS env var
    # (observed: JAX_PLATFORMS=cpu still hangs in tunnel init); the config
    # update after import is the only reliable way to force a platform —
    # same mechanism tests/conftest.py uses.
    force = os.environ.get("ST_FORCE_PLATFORM")
    if force:
        jax.config.update("jax_platforms", force)

    # Parent watches for this marker: it distinguishes "backend init hung or
    # failed" (retry chip with backoff / skip to CPU) from "backend fine but
    # the codec/measurement failed" (fall back to the XLA codec on-chip).
    # The third token classifies the backend as tpu/other using the ONE
    # source of truth for plugin-name knowledge (codec_pallas._interpret —
    # the supervisor itself must stay jax-free and cannot classify).
    from shared_tensor_tpu.ops import codec_pallas as _cp

    kind = "other" if _cp._interpret() else "tpu"
    print(
        f"ST_BACKEND_UP {jax.default_backend()} {kind}",
        file=sys.stderr,
        flush=True,
    )

    if codec_name == "pallas":
        codec = _cp

        if codec._interpret():
            # Interpret-mode Pallas is orders of magnitude slower than the
            # XLA codec and would masquerade as a kernel number — fail fast
            # so the supervisor falls through to the honest arm.
            raise RuntimeError(
                "pallas arm needs a TPU backend; "
                f"got {jax.default_backend()} (would run interpret mode)"
            )
    else:
        from shared_tensor_tpu.ops import codec

    from shared_tensor_tpu.config import ScalePolicy
    from shared_tensor_tpu.utils.timing import codec_frame_time

    budget = float(os.environ.get("ST_TIMING_BUDGET_S", "120"))
    t_frame = codec_frame_time(
        codec, N, ScalePolicy.POW2_RMS, target_seconds=3.0, budget_s=budget
    )
    _print_result(t_frame, jax.default_backend(), codec_name)


def _worker_host() -> None:
    """The host production tier (ops/codec_np.py: numpy semantics over the
    AVX-512 C loops in native/stcodec.c) — synchronous host work, timed
    directly, NO jax backend (see _worker). This is what a CPU peer actually
    runs, and it beats the reference's 202 M elem/s loops ~5x per core
    (HOST_CODEC_r03.jsonl), so the no-chip fallback still clears the
    baseline."""
    import numpy as np

    from shared_tensor_tpu.config import ScalePolicy
    from shared_tensor_tpu.ops import codec_np
    from shared_tensor_tpu.ops.table import make_spec

    if codec_np._native() is None:
        raise RuntimeError("native libstcodec.so unavailable (no toolchain?)")
    print("ST_BACKEND_UP cpu other", file=sys.stderr, flush=True)
    spec = make_spec(np.zeros(N, np.float32))
    rng = np.random.default_rng(0)
    resid = rng.uniform(-1.0, 1.0, N).astype(np.float32)
    values = rng.uniform(-1.0, 1.0, N).astype(np.float32)

    def frame():  # one full link frame: sender half + receiver half
        scales, words, _ = codec_np.quantize_table_np(
            resid, spec, ScalePolicy.POW2_RMS
        )
        codec_np.apply_table_many_np((values,), scales, words, spec)

    for _ in range(3):
        frame()
    budget = float(os.environ.get("ST_TIMING_BUDGET_S", "120"))
    t0 = time.perf_counter()
    reps = 0
    while True:
        frame()
        reps += 1
        dt = time.perf_counter() - t0
        if dt >= min(3.0, budget) and reps >= 5:
            break
    _print_result(dt / reps, "cpu", "host")


def _worker_engine() -> None:
    """The host production data plane measured END TO END: the native engine
    (native/stengine.cpp) driving a 2-process loopback sync at n = 1 Mi
    through the full stack (quantize -> encode -> TCP -> decode -> flood
    apply -> ACK). This is the same methodology as the baseline's own 242
    f/s / 1.01 GB/s measurement (BASELINE.md E2E table, reference
    src/sharedtensor.c:113-189), so it is the most comparable no-chip
    number — and it clears the baseline ~4x (ENGINE_r04.json), vs ~2.9x for
    the bare codec component loop. Reported rate: the child's delivered
    frames_in/s on its one uplink (per-link, one direction — conservative,
    the link also carries the reverse stream)."""
    import multiprocessing as mp

    from shared_tensor_tpu.comm.engine import load_engine

    if load_engine() is None:
        # Cheap upfront probe (the host arm's codec_np._native() pattern):
        # without it a toolchain-less box burns ~13 s of spawn + measure
        # before discovering the run must be discarded.
        raise RuntimeError("native libstengine.so unavailable (no toolchain?)")

    sys.path.insert(
        0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "benchmarks")
    )
    import engine_bench

    print("ST_BACKEND_UP cpu other", file=sys.stderr, flush=True)
    mp.set_start_method("spawn", force=True)
    row = engine_bench.run_size(N)
    if not (row.get("engine") and row.get("master_engine")):
        # Engine must attach on BOTH peers: a Python-tier rate on either end
        # (build race, partial toolchain failure in one spawn) must not
        # masquerade as the engine number; fall through to the host arm.
        raise RuntimeError(f"native engine did not attach on both peers: {row}")
    fps = row["frames_in_per_s"]
    if fps <= 0:
        raise RuntimeError(f"engine e2e measured no frames: {row}")
    _print_result(1.0 / fps, "cpu", "engine-e2e")


# ------------------------------------------------------------ supervisor ----


def _run_arm(platform: str | None, codec_name: str, timeout_s: float):
    """One watchdogged measurement subprocess.

    Returns (parsed_json_or_None, backend: (name, is_tpu) | None,
    outcome: str, stderr_tail: str). ``backend`` comes from the worker's
    ``ST_BACKEND_UP <name> <tpu|other>`` marker (None = backend never
    initialized). ``platform=None`` keeps the ambient JAX_PLATFORMS (the
    real chip under the driver); "cpu" forces the CPU fallback.
    """
    global _ACTIVE_WORKER
    env = dict(os.environ)
    if platform is not None:
        env["JAX_PLATFORMS"] = platform
        env["ST_FORCE_PLATFORM"] = platform
    if platform == "cpu":
        # Strip the TPU-plugin site hook: a process that merely HAS it on
        # PYTHONPATH claims the (single) chip grant at interpreter start and
        # hangs BEFORE main() when the grant is wedged (observed; see
        # .claude/skills/verify/SKILL.md) — the exact situation the CPU
        # fallback exists for. The config-update-after-import trick cannot
        # help a process that never reaches main.
        parts = [
            p for p in env.get("PYTHONPATH", "").split(os.pathsep)
            if p and os.path.basename(os.path.normpath(p)) != ".axon_site"
        ]
        env["PYTHONPATH"] = os.pathsep.join(parts)
    # Leave headroom inside the subprocess for backend init + the one compile.
    env["ST_TIMING_BUDGET_S"] = str(max(20.0, timeout_s - 90.0))
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--worker", codec_name],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        cwd=os.path.dirname(os.path.abspath(__file__)),
        # Own process group: the engine arm forks multiprocessing children
        # (master/child peers); killing only the direct worker would leave
        # them streaming against the single vCPU while the NEXT arm measures
        # (the 2.7x-contention failure mode this file documents).
        start_new_session=True,
    )
    _ACTIVE_WORKER = proc  # so the SIGTERM handler can reap it (no orphans)
    try:
        stdout, stderr = proc.communicate(timeout=timeout_s)
        timed_out = False
    except subprocess.TimeoutExpired:
        _kill_worker_tree(proc)
        stdout, stderr = proc.communicate()
        stdout, stderr = stdout or "", stderr or ""
        timed_out = True
    finally:
        _ACTIVE_WORKER = None

    backend = None
    for line in stderr.splitlines():
        if line.startswith("ST_BACKEND_UP"):
            parts = line.split()
            backend = (
                parts[1] if len(parts) > 1 else "unknown",
                len(parts) > 2 and parts[2] == "tpu",
            )
            break
    backend_up = backend is not None
    parsed = None
    for line in reversed(stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                parsed = json.loads(line)
                break
            except json.JSONDecodeError:
                continue
    if parsed is not None:
        outcome = "ok"
    elif timed_out:
        outcome = "timeout-backend-init" if not backend_up else "timeout-measuring"
    elif not backend_up:
        outcome = "backend-init-failed"
    else:
        outcome = "measurement-failed"
    return parsed, backend, outcome, stderr[-2000:]


def main() -> None:
    attempts: list[dict] = []
    best: dict | None = None
    chip_state = "not-tried"

    def note(platform, codec, outcome, err_tail=""):
        entry = {
            "platform": platform or "ambient",
            "codec": codec,
            "outcome": outcome,
        }
        if outcome != "ok" and err_tail:
            # Keep the root cause (Mosaic rejection, init error) in the
            # artifact — an outcome string alone is undebuggable.
            entry["stderr_tail"] = err_tail[-500:]
        attempts.append(entry)

    # On SIGTERM/SIGINT (driver timeout), still emit whatever we know — and
    # kill the in-flight worker first: an orphaned jax subprocess hung in
    # tunnel init would keep the TPU grant claimed for the NEXT run (the
    # exact wedge this bench exists to survive).
    def _sig(signum, frame):
        if _ACTIVE_WORKER is not None:
            _kill_worker_tree(_ACTIVE_WORKER)
        _emit(_error_result(attempts, f"signal {signum} before any arm finished"))
        os._exit(1)

    signal.signal(signal.SIGTERM, _sig)
    signal.signal(signal.SIGINT, _sig)

    # Phase A: the real chip (ambient platform). Retry with backoff if the
    # chip is claimed/wedged (VERDICT.md next-round item 2); never burn the
    # CPU reserve.
    def _tpu_like(backend) -> bool:
        return backend is not None and backend[1]

    tries = 0
    while best is None and tries < 3:
        budget_left = _remaining() - CPU_RESERVE_S
        if budget_left < 75:
            break
        parsed, backend, outcome, err = _run_arm(None, "pallas", min(budget_left, 270.0))
        note(None, "pallas", outcome, err)
        if _tpu_like(backend):
            chip_state = "up"
        elif chip_state == "not-tried":
            chip_state = "wedged-or-unavailable"
        if parsed is not None:
            best = parsed
            break
        if backend is not None:
            # Backend is fine; the Pallas path itself failed (e.g. Mosaic
            # rejection). Do NOT re-enter Pallas — try the XLA codec on the
            # SAME (TPU) backend. If the ambient backend instead resolved
            # to CPU (no TPU plugin registered at all), skip straight to
            # Phase B: its ladder puts the native-engine E2E first and
            # XLA-CPU LAST — before r07 this branch ran XLA-CPU here and
            # its ~2.6 GB/s short-circuited the ~6x-better engine arm
            # whenever the backend came up as CPU instead of hanging.
            if not _tpu_like(backend):
                break
            budget_left = _remaining() - CPU_RESERVE_S
            if budget_left >= 75:
                parsed, backend, outcome, err = _run_arm(
                    None, "xla", min(budget_left, 270.0)
                )
                note(None, "xla", outcome, err)
                if parsed is not None:
                    best = parsed
            break
        tries += 1
        backoff = min(20.0 * tries, max(0.0, _remaining() - CPU_RESERVE_S - 75))
        if backoff > 0:
            time.sleep(backoff)

    # Phase B: CPU fallback — a degraded but real number beats no number.
    # Arm ladder: the native-engine E2E loopback first (the host production
    # data plane, methodology-matched to the baseline's own E2E probe, ~4x),
    # then the host codec component loop (numpy + AVX-512 C, ~2.9x), then
    # pure-XLA as the last resort. Each arm's timeout leaves a 20 s floor
    # for every arm still behind it (and the 15 s minimum stays below that
    # floor), so one hung fallback (e.g. engine port trouble) cannot starve
    # the simpler, more reliable ones — even under a reduced
    # ST_BENCH_BUDGET_S.
    cpu_arms = ("engine", "host", "xla")
    for i, cpu_codec in enumerate(cpu_arms):
        if best is not None or _remaining() <= 15:
            break
        arms_behind = len(cpu_arms) - 1 - i
        timeout_s = min(max(15.0, _remaining() - 10 - 20.0 * arms_behind), 100.0)
        parsed, _, outcome, err = _run_arm("cpu", cpu_codec, timeout_s)
        note("cpu", cpu_codec, outcome, err)
        if parsed is not None:
            best = parsed
            best["detail"]["degraded"] = "cpu-fallback (real chip unavailable)"

    if best is None:
        best = _error_result(attempts, "no arm completed within budget")
    best.setdefault("detail", {})
    best["detail"]["attempts"] = attempts
    best["detail"]["chip_state"] = chip_state
    # Top-level tier label (round-3 verdict Weak #1): round-over-round
    # comparisons must not silently cross tiers — a skim reader of
    # BENCH_r{N}.json sees at the top level whether this is the on-chip
    # number or a degraded host capture.
    best.setdefault(
        "tier", "host-fallback" if best["detail"].get("degraded") else "device"
    )
    _emit(best)


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--worker":
        _worker(sys.argv[2])
    else:
        try:
            main()
        except Exception as e:  # the one-JSON-line contract holds no matter what
            import traceback

            traceback.print_exc(file=sys.stderr)
            _emit(_error_result([], f"supervisor crashed: {type(e).__name__}: {e}"))
            sys.exit(1)
