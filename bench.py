"""Headline benchmark: approximate-delta sync bandwidth of the fused codec
path on one chip, in equivalent applied-fp32-delta GB/s per link.

Methodology (matches BASELINE.md's yardstick): the reference's 2-node
loopback E2E sync at n = 1 Mi elements moves 1.01 GB/s of equivalent fp32
deltas per link, and is codec-CPU-bound, not network-bound (SURVEY.md §6 —
the wire carries only 0.03 GB/s; one core saturates on the quantize/apply
loops, which is exactly the work reference README.md:47 wanted moved to an
accelerator kernel). This bench therefore times that bottleneck work on the
TPU: per frame, one full sender half (pow2-RMS scale + sign-quantize +
bit-pack + error feedback, Pallas) plus one receiver half (unpack + apply,
Pallas) on an n = 1 Mi buffer — the identical per-link per-frame math at
identical approximation error (the codec is bit-for-bit the reference
arithmetic; tests/test_codec*.py pin that). Frames are chained device-side
via lax.scan into multi-second runs so tunnel dispatch latency is a small
bias that only understates the result; gaussian residuals keep a nonzero
scale throughout, so every frame does the full (non-idle) codec work.

Prints ONE JSON line: equivalent-delta GB/s and the ratio vs the 1.01 GB/s
reference baseline.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp

N = 1 << 20  # 1 Mi elements — BASELINE.md's headline E2E config
BASELINE_GBPS = 1.01


def _bench(codec, codec_name: str) -> dict:
    """Long-chain device-side timing (utils/timing.py): thousands of frames
    per dispatch, so tunnel latency is a small conservative bias."""
    from shared_tensor_tpu.config import ScalePolicy
    from shared_tensor_tpu.utils.timing import codec_frame_time

    t_frame = codec_frame_time(codec, N, ScalePolicy.POW2_RMS)
    fps = 1.0 / t_frame
    equiv_gbps = fps * N * 4 / 1e9
    return {
        "metric": "sync_bandwidth_equiv_fp32_per_link",
        "value": round(equiv_gbps, 3),
        "unit": "GB/s",
        "vs_baseline": round(equiv_gbps / BASELINE_GBPS, 2),
        "detail": {
            "n_elements": N,
            "frames_per_s": round(fps, 1),
            "backend": jax.default_backend(),
            "codec": codec_name,
            "wire_gbps": round(fps * (N / 8 + 4) / 1e9, 4),
        },
    }


def main() -> None:
    import sys
    import traceback

    try:
        from shared_tensor_tpu.ops import codec_pallas as codec
        result = _bench(codec, "pallas")
    except Exception:  # Pallas path unavailable: pure-JAX/XLA fallback.
        # Loud + recorded in the JSON (detail.codec) so a fallback can never
        # masquerade as a Pallas result.
        traceback.print_exc(file=sys.stderr)
        print("bench: Pallas codec failed, falling back to XLA codec", file=sys.stderr)
        from shared_tensor_tpu.ops import codec
        result = _bench(codec, "xla-fallback")
    print(json.dumps(result))


if __name__ == "__main__":
    main()
