"""Headline benchmark: approximate-delta sync bandwidth of the fused codec
path on one chip, in equivalent applied-fp32-delta GB/s per link.

Methodology (matches BASELINE.md's yardstick): the reference's 2-node
loopback E2E sync at n = 1 Mi elements moves 1.01 GB/s of equivalent fp32
deltas per link, and is codec-CPU-bound, not network-bound (SURVEY.md §6 —
the wire carries only 0.03 GB/s; one core saturates on the quantize/apply
loops, which is exactly the work reference README.md:47 wanted moved to an
accelerator kernel). This bench therefore times that bottleneck work on the
TPU: per frame, one full sender half (pow2-RMS scale + sign-quantize +
bit-pack + error feedback, Pallas) plus one receiver half (unpack + apply,
Pallas) on an n = 1 Mi buffer — the identical per-link per-frame math at
identical approximation error (the codec is bit-for-bit the reference
arithmetic; tests/test_codec*.py pin that). Frames are chained device-side
via lax.scan and timed by the marginal-rate method (long chain minus short
chain) so tunnel dispatch latency neither flatters nor masks the result;
gaussian residuals keep a nonzero scale throughout, so every frame does the
full (non-idle) codec work.

Prints ONE JSON line: equivalent-delta GB/s and the ratio vs the 1.01 GB/s
reference baseline.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp

N = 1 << 20  # 1 Mi elements — BASELINE.md's headline E2E config
BASELINE_GBPS = 1.01


def _bench(codec, codec_name: str) -> dict:
    """Marginal-rate timing: through the axon tunnel, dispatch + completion
    signaling costs ~0.1 s regardless of work, and ``block_until_ready`` can
    return optimistically — so each measurement chains L frames device-side
    in one program, forces TRUE completion by fetching a scalar that depends
    on the final frame, and the per-frame time comes from the difference
    between a long and a short chain (fixed overhead cancels)."""
    from functools import partial

    from shared_tensor_tpu.config import ScalePolicy

    @partial(jax.jit, static_argnames=("length",), donate_argnums=(0, 1))
    def group(resid, values, length):
        def body(carry, _):
            r, v = carry
            frame, r = codec.quantize(r, N, ScalePolicy.POW2_RMS)
            v = codec.apply_frame(v, frame, N)
            return (r, v), frame.scale

        (r, v), scales = jax.lax.scan(body, (resid, values), None, length=length)
        # The fetched scalar depends on both chains (r via scales, v
        # directly), so neither half can be dead-code-eliminated and the
        # fetch waits for the whole program.
        return r, v, scales[-1] + v[0]

    def timed(length: int) -> float:
        best = float("inf")
        for rep in range(3):
            r = jax.random.normal(jax.random.key(rep), (N,), jnp.float32)
            v = jnp.zeros((N,), jnp.float32)
            jax.block_until_ready((r, v))
            t0 = time.perf_counter()
            _, _, probe = group(r, v, length)
            float(probe)  # forces completion through the tunnel
            best = min(best, time.perf_counter() - t0)
        return best

    short, long_ = 16, 144
    timed(short)  # warmup/compile both lengths
    timed(long_)
    t_frame = (timed(long_) - timed(short)) / (long_ - short)

    fps = 1.0 / t_frame
    equiv_gbps = fps * N * 4 / 1e9
    return {
        "metric": "sync_bandwidth_equiv_fp32_per_link",
        "value": round(equiv_gbps, 3),
        "unit": "GB/s",
        "vs_baseline": round(equiv_gbps / BASELINE_GBPS, 2),
        "detail": {
            "n_elements": N,
            "frames_per_s": round(fps, 1),
            "backend": jax.default_backend(),
            "codec": codec_name,
            "wire_gbps": round(fps * (N / 8 + 4) / 1e9, 4),
        },
    }


def main() -> None:
    import sys
    import traceback

    try:
        from shared_tensor_tpu.ops import codec_pallas as codec
        result = _bench(codec, "pallas")
    except Exception:  # Pallas path unavailable: pure-JAX/XLA fallback.
        # Loud + recorded in the JSON (detail.codec) so a fallback can never
        # masquerade as a Pallas result.
        traceback.print_exc(file=sys.stderr)
        print("bench: Pallas codec failed, falling back to XLA codec", file=sys.stderr)
        from shared_tensor_tpu.ops import codec
        result = _bench(codec, "xla-fallback")
    print(json.dumps(result))


if __name__ == "__main__":
    main()
